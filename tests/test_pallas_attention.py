"""Flash-attention kernel correctness (interpret mode on CPU; the same kernel
compiles for TPU via Mosaic — bench.py exercises that path)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from xotorch_support_jetson_tpu.ops.attention import gqa_attention
from xotorch_support_jetson_tpu.ops.pallas_attention import BLOCK_K, BLOCK_Q, flash_attention_prefill, flash_supported


def _make(B=2, Sq=256, Skv=256, Hq=4, Hkv=2, hd=64, seed=0):
  ks = jax.random.split(jax.random.PRNGKey(seed), 3)
  q = jax.random.normal(ks[0], (B, Sq, Hq, hd), jnp.float32)
  k = jax.random.normal(ks[1], (B, Skv, Hkv, hd), jnp.float32)
  v = jax.random.normal(ks[2], (B, Skv, Hkv, hd), jnp.float32)
  return q, k, v


@pytest.mark.parametrize("Sq,Skv,offset", [(256, 256, 0), (128, 512, 0), (128, 384, 128)])
def test_flash_matches_dense(Sq, Skv, offset):
  q, k, v = _make(Sq=Sq, Skv=Skv)
  q_pos = jnp.broadcast_to(offset + jnp.arange(Sq, dtype=jnp.int32), (q.shape[0], Sq))
  kv_pos = jnp.arange(Skv, dtype=jnp.int32)
  with jax.default_matmul_precision("highest"):
    dense = gqa_attention(q, k, v, q_pos, kv_pos)
    flash = flash_attention_prefill(q, k, v, q_offset=offset, interpret=True)
  np.testing.assert_allclose(np.asarray(flash), np.asarray(dense), rtol=2e-5, atol=2e-5)


def test_flash_masks_garbage_beyond_positions():
  """Cache slots beyond the prompt hold junk; positional masking must hide it."""
  q, k, v = _make(Sq=128, Skv=256)
  # Poison slots >= 128 with huge values.
  k = k.at[:, 128:].set(1e4)
  v = v.at[:, 128:].set(1e4)
  q_pos = jnp.broadcast_to(jnp.arange(128, dtype=jnp.int32), (2, 128))
  with jax.default_matmul_precision("highest"):
    dense = gqa_attention(q, k[:, :128], v[:, :128], q_pos, jnp.arange(128, dtype=jnp.int32))
    flash = flash_attention_prefill(q, k, v, q_offset=0, interpret=True)
  np.testing.assert_allclose(np.asarray(flash), np.asarray(dense), rtol=2e-5, atol=2e-5)


def test_flash_prefill_half_specified_quant_raises():
  """Passing only one of k_scale/v_scale is a caller bug (the other leaf
  would be silently ignored / int8 codes read as values): fail loudly."""
  q, k, v = _make(Sq=128, Skv=128)
  scale = jnp.ones((2, 128, 2, 1), jnp.float32)
  with pytest.raises(ValueError, match="k_scale and v_scale"):
    flash_attention_prefill(q, k, v, k_scale=scale, interpret=True)
  with pytest.raises(ValueError, match="k_scale and v_scale"):
    flash_attention_prefill(q, k, v, v_scale=scale, interpret=True)


def test_flash_supported_gating(monkeypatch):
  assert not flash_supported((1, 100, 4, 64), 256, platform="tpu")  # Sq not blocked
  assert not flash_supported((1, 128, 4, 63), 256, platform="tpu")  # odd head dim
  assert not flash_supported((1, 128, 4, 64), 200, platform="tpu")  # kv not blocked
  assert flash_supported((1, 128, 4, 64), 256, platform="tpu")
  assert not flash_supported((1, 128, 4, 64), 256, platform="cpu")
  monkeypatch.setenv("XOT_TPU_NO_FLASH", "1")
  assert not flash_supported((1, 128, 4, 64), 256, platform="tpu")


def test_flash_decode_matches_dense_reference():
  """Flash-decode (split-K over the cache with block-diagonal queries) ==
  dense attention for ragged per-row positions, including row position 0."""
  from xotorch_support_jetson_tpu.ops.pallas_attention import flash_decode_attention

  rng = np.random.default_rng(7)
  B, Hq, Hkv, hd, Skv = 2, 8, 4, 64, 128
  q = jnp.asarray(rng.normal(size=(B, 1, Hq, hd)), jnp.float32)
  k = jnp.asarray(rng.normal(size=(B, Skv, Hkv, hd)), jnp.float32)
  v = jnp.asarray(rng.normal(size=(B, Skv, Hkv, hd)), jnp.float32)
  for pos in ([37, 12], [127, 0]):
    q_pos = jnp.asarray(pos, jnp.int32)[:, None]
    with jax.default_matmul_precision("highest"):
      dense = gqa_attention(q, k, v, q_pos, jnp.arange(Skv, dtype=jnp.int32))
      flash = flash_decode_attention(q, k, v, q_pos, interpret=True)
    np.testing.assert_allclose(np.asarray(flash), np.asarray(dense), rtol=2e-5, atol=2e-5)


def test_flash_decode_gating(monkeypatch):
  from xotorch_support_jetson_tpu.ops.pallas_attention import flash_decode_supported

  monkeypatch.setenv("XOT_TPU_FLASH_DECODE", "1")
  assert flash_decode_supported((1, 1, 32, 64), 16384, platform="tpu")
  assert not flash_decode_supported((1, 1, 32, 64), 4096, platform="tpu")  # below threshold
  assert not flash_decode_supported((1, 2, 32, 64), 16384, platform="tpu")  # not a decode step
  assert not flash_decode_supported((1, 1, 32, 64), 16384, platform="cpu")
  monkeypatch.delenv("XOT_TPU_FLASH_DECODE")
  assert not flash_decode_supported((1, 1, 32, 64), 16384, platform="tpu")  # opt-in


def test_flash_decode_multi_block_carry(monkeypatch):
  """Force multiple kv blocks so the cross-block online-softmax carry, the
  clamped DMA index, and the block-skip actually run (BLOCK_D shrunk)."""
  import xotorch_support_jetson_tpu.ops.pallas_attention as pa

  monkeypatch.setattr(pa, "BLOCK_D", 64)
  rng = np.random.default_rng(11)
  B, Hq, Hkv, hd, Skv = 2, 8, 4, 64, 256  # 4 blocks of 64
  q = jnp.asarray(rng.normal(size=(B, 1, Hq, hd)), jnp.float32)
  k = jnp.asarray(rng.normal(size=(B, Skv, Hkv, hd)), jnp.float32)
  v = jnp.asarray(rng.normal(size=(B, Skv, Hkv, hd)), jnp.float32)
  for pos in ([255, 100], [70, 0]):  # full span / mid-block raggedness
    q_pos = jnp.asarray(pos, jnp.int32)[:, None]
    with jax.default_matmul_precision("highest"):
      dense = gqa_attention(q, k, v, q_pos, jnp.arange(Skv, dtype=jnp.int32))
      flash = pa.flash_decode_attention(q, k, v, q_pos, interpret=True)
    np.testing.assert_allclose(np.asarray(flash), np.asarray(dense), rtol=2e-5, atol=2e-5)
