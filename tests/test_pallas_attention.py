"""Flash-attention kernel correctness (interpret mode on CPU; the same kernel
compiles for TPU via Mosaic — bench.py exercises that path)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from xotorch_support_jetson_tpu.ops.attention import gqa_attention
from xotorch_support_jetson_tpu.ops.pallas_attention import BLOCK_K, BLOCK_Q, flash_attention_prefill, flash_supported


def _make(B=2, Sq=256, Skv=256, Hq=4, Hkv=2, hd=64, seed=0):
  ks = jax.random.split(jax.random.PRNGKey(seed), 3)
  q = jax.random.normal(ks[0], (B, Sq, Hq, hd), jnp.float32)
  k = jax.random.normal(ks[1], (B, Skv, Hkv, hd), jnp.float32)
  v = jax.random.normal(ks[2], (B, Skv, Hkv, hd), jnp.float32)
  return q, k, v


@pytest.mark.parametrize("Sq,Skv,offset", [(256, 256, 0), (128, 512, 0), (128, 384, 128)])
def test_flash_matches_dense(Sq, Skv, offset):
  q, k, v = _make(Sq=Sq, Skv=Skv)
  q_pos = jnp.broadcast_to(offset + jnp.arange(Sq, dtype=jnp.int32), (q.shape[0], Sq))
  kv_pos = jnp.arange(Skv, dtype=jnp.int32)
  with jax.default_matmul_precision("highest"):
    dense = gqa_attention(q, k, v, q_pos, kv_pos)
    flash = flash_attention_prefill(q, k, v, q_offset=offset, interpret=True)
  np.testing.assert_allclose(np.asarray(flash), np.asarray(dense), rtol=2e-5, atol=2e-5)


def test_flash_masks_garbage_beyond_positions():
  """Cache slots beyond the prompt hold junk; positional masking must hide it."""
  q, k, v = _make(Sq=128, Skv=256)
  # Poison slots >= 128 with huge values.
  k = k.at[:, 128:].set(1e4)
  v = v.at[:, 128:].set(1e4)
  q_pos = jnp.broadcast_to(jnp.arange(128, dtype=jnp.int32), (2, 128))
  with jax.default_matmul_precision("highest"):
    dense = gqa_attention(q, k[:, :128], v[:, :128], q_pos, jnp.arange(128, dtype=jnp.int32))
    flash = flash_attention_prefill(q, k, v, q_offset=0, interpret=True)
  np.testing.assert_allclose(np.asarray(flash), np.asarray(dense), rtol=2e-5, atol=2e-5)


def test_flash_supported_gating(monkeypatch):
  assert not flash_supported((1, 100, 4, 64), 256, platform="tpu")  # Sq not blocked
  assert not flash_supported((1, 128, 4, 63), 256, platform="tpu")  # odd head dim
  assert not flash_supported((1, 128, 4, 64), 200, platform="tpu")  # kv not blocked
  assert flash_supported((1, 128, 4, 64), 256, platform="tpu")
  assert not flash_supported((1, 128, 4, 64), 256, platform="cpu")
  monkeypatch.setenv("XOT_TPU_NO_FLASH", "1")
  assert not flash_supported((1, 128, 4, 64), 256, platform="tpu")
