"""Mixed prefill+decode ticks (ISSUE 14, inference/batch_scheduler.py).

The contract: with ``XOT_TPU_MIXED_TICK`` on (the default) a chunked prefill
advances by SLO-budgeted slices fused INTO the batched decode dispatches
(``models/decoder.py fused_mixed_paged_batch_decode``) instead of stalling
every resident stream for whole alternating prefill chunks — and greedy
output is TOKEN-IDENTICAL to the alternating baseline across paged
int8-KV/int4-KV × lookahead on/off × QoS preempt-resume mid-mixed-tick.
``XOT_TPU_MIXED_TICK=0`` is byte-identical off: the mixed program is never
dispatched (poison-pinned). The tick planner never exceeds the per-tick
budget, and neither side starves: a staged prefill advances every tick while
decode rows keep emitting.
"""

import asyncio

import jax
import numpy as np
import pytest

from tests.test_batched import _single_row_reference
from xotorch_support_jetson_tpu.inference.batch_scheduler import BatchedServer
from xotorch_support_jetson_tpu.inference.jax_engine import JaxShardedInferenceEngine
from xotorch_support_jetson_tpu.inference.paging import select_mixed_budget
from xotorch_support_jetson_tpu.models.config import tiny_test_config
from xotorch_support_jetson_tpu.models.decoder import full_model_params

# The suite-shared tiny geometry (test_batched/test_lookahead use the same
# cfg), so compiled programs dedup across modules in one pytest process —
# this file must stay cheap inside the tier-1 timing budget.
CFG = tiny_test_config(n_layers=2, max_seq_len=128)
KEY = jax.random.PRNGKey(0)
PARAMS, SHARD = full_model_params(KEY, CFG)
LONG = [(i % 90) + 3 for i in range(80)]  # 5 chunks at XOT_TPU_PREFILL_CHUNK=16
PROMPTS = [[3, 25, 9], LONG, [7, 1, 88, 42, 5]]


def _engine():
  engine = JaxShardedInferenceEngine(use_local_mesh=False)
  engine.load_test_model(SHARD, CFG, PARAMS)
  return engine


def _spy_mixed(server, calls, poison=False):
  """Record (start, end) of every mixed dispatch's prefill slice — or
  poison the op so an off-mode dispatch fails loudly."""
  orig = server.ops.mixed_paged_batch_decode

  def wrapped(*a, **kw):
    if poison:
      raise AssertionError("mixed program dispatched with XOT_TPU_MIXED_TICK=0")
    calls.append((int(kw["pf_prefix"][0]), int(kw["pf_end"][0])))
    return orig(*a, **kw)

  server.ops.mixed_paged_batch_decode = wrapped


def _serve(server, prompts, n_gen, priorities=None):
  streams: dict[str, list] = {}

  async def run():
    def emit(rid, toks, finished):
      streams.setdefault(rid, []).extend(toks)

    return await asyncio.gather(
      *(
        server.submit(
          f"r{i}", np.asarray(p, np.int32), max_tokens=n_gen, temp=0.0, top_k=35, eos_ids=(),
          emit=emit, priority=(priorities[i] if priorities else "standard"),
        )
        for i, p in enumerate(prompts)
      )
    )

  outs = asyncio.run(run())
  return outs, streams


def test_mixed_env_knob(monkeypatch):
  monkeypatch.setenv("XOT_TPU_PREFILL_CHUNK", "16")
  engine = _engine()
  assert BatchedServer(engine).mixed  # default ON
  monkeypatch.setenv("XOT_TPU_MIXED_TICK", "0")
  assert not BatchedServer(engine).mixed
  monkeypatch.setenv("XOT_TPU_MIXED_TICK", "1")
  assert BatchedServer(engine).mixed


def test_select_mixed_budget_policy(monkeypatch):
  """Budget-policy properties: always within [floor-or-cap, cap], monotone
  non-increasing in burn, full cap when idle, env force-pin clamps."""
  monkeypatch.delenv("XOT_TPU_MIXED_BUDGET", raising=False)
  for cap in (16, 64, 2048):
    assert select_mixed_budget(cap, None, residents=0) == cap  # idle: full chunk
    assert select_mixed_budget(cap, 50.0, residents=0) == cap  # idle wins regardless of burn
    prev = cap
    for burn in (None, 0.0, 0.3, 1.0, 2.0, 5.0, 50.0):
      b = select_mixed_budget(cap, burn, residents=3)
      assert min(16, cap) <= b <= cap
      assert b <= prev  # shrinks (weakly) as burn rises
      prev = b
    assert select_mixed_budget(cap, None, residents=3) == max(cap // 2, min(16, cap))
    # Backlog growth: with K admissions mid-prefill and ITL not burning the
    # slice grows toward the cap (small slices never shrink the TOTAL stall
    # a backlog imposes — they only multiply the ticks TTFT waits through);
    # measured burn >= 1 keeps the table's shrink UNSCALED (smoothing is
    # what a burning objective pays TTFT for).
    assert select_mixed_budget(cap, None, residents=3, backlog=4) == cap
    assert select_mixed_budget(cap, 0.5, residents=3, backlog=2) <= cap
    assert select_mixed_budget(cap, 2.0, residents=3, backlog=8) == select_mixed_budget(cap, 2.0, residents=3)
  monkeypatch.setenv("XOT_TPU_MIXED_BUDGET", "24")
  assert select_mixed_budget(2048, 50.0, residents=8) == 24  # force-pin wins
  assert select_mixed_budget(16, None, residents=1) == 16  # ...clamped to cap


@pytest.mark.parametrize("kv_quant", ["int8", "int4"])
@pytest.mark.parametrize("lookahead", [True, False])
def test_mixed_ab_identity(monkeypatch, kv_quant, lookahead):
  """The A/B matrix: mixed vs alternating greedy streams are token-identical
  (and equal to the solo reference) over paged int8-KV and int4-KV pools,
  lookahead on and off — with the mixed program VERIFIABLY dispatching in
  the on arm and poisoned-never-called in the off arm."""
  monkeypatch.setenv("XOT_TPU_PAGED", "1")
  monkeypatch.setenv("XOT_TPU_KV_QUANT", kv_quant)
  monkeypatch.setenv("XOT_TPU_PAGE_SIZE", "16")
  monkeypatch.setenv("XOT_TPU_PREFILL_CHUNK", "16")
  n_gen = 8
  outs = {}
  for mixed in (False, True):
    monkeypatch.setenv("XOT_TPU_MIXED_TICK", "1" if mixed else "0")
    server = BatchedServer(_engine(), n_slots=4, chunk=4, lookahead=lookahead)
    calls: list = []
    _spy_mixed(server, calls, poison=not mixed)
    outs[mixed], streams = _serve(server, PROMPTS, n_gen)
    for i, o in enumerate(outs[mixed]):
      assert streams[f"r{i}"] == o
    server.shutdown()
    if mixed:
      # The long prompt's later chunks rode mixed ticks (the short rows
      # admitted alongside are still decoding), each slice within budget.
      assert calls, "mixed program never dispatched — the A/B is vacuous"
      assert all(0 < e - s <= 16 for s, e in calls)
  assert outs[True] == outs[False]
  expected = [_single_row_reference(PARAMS, SHARD, p, n_gen - 1) for p in PROMPTS]
  assert outs[True] == expected


def test_mixed_slice_pad_stays_pow2_near_window(monkeypatch):
  """Near the context window the planner SHRINKS the slice so its padded
  dispatch shape stays a power of two inside the scatter-clamp bound
  (prefix + pad <= max_seq) — clamping the pad to an arbitrary width would
  trace a fresh XLA compile per near-window slice, the exact recompile the
  traced budget exists to avoid."""
  from xotorch_support_jetson_tpu.inference.batch_scheduler import _Ready
  from xotorch_support_jetson_tpu.inference.sched_admission import _Request

  monkeypatch.setenv("XOT_TPU_PREFILL_CHUNK", "512")
  monkeypatch.setenv("XOT_TPU_MIXED_BUDGET", "512")
  server = BatchedServer(_engine(), n_slots=2, chunk=4)
  server.max_seq = 1024
  server.slots[0] = "resident"  # placeholder: the planner only checks identity-vs-None
  req = _Request(request_id="w", tokens=np.zeros(1000, np.int32), max_tokens=4, temp=0.0, top_k=1, eos_ids=(), emit=lambda *a: None)
  server._prefilling.append(_Ready(req=req, row=1, pad_to=0, prefix_len=596))
  r, start, end = server._mixed_intent(None)
  # Budget 512 would slice 276 (remaining 404 - final cap 128), whose pow2
  # pad 512 exceeds the 428-token window room: the slice shrinks to 256.
  assert (start, end - start) == (596, 256)
  pad = 1
  while pad < end - start:
    pad *= 2
  assert start + pad <= server.max_seq


def test_mixed_preempt_resume_mid_mixed_tick(monkeypatch):
  """QoS preempt-resume lands mid-mixed-tick: an interactive long-prompt
  arrival preempts the batch-class resident, then its chunked prefill rides
  mixed ticks next to the surviving interactive resident; the preempted row
  resumes token-identically. Pinned A/B vs the alternating scheduler."""
  monkeypatch.setenv("XOT_TPU_PAGED", "1")
  monkeypatch.setenv("XOT_TPU_KV_QUANT", "int8")
  monkeypatch.setenv("XOT_TPU_PAGE_SIZE", "16")
  monkeypatch.setenv("XOT_TPU_PREFILL_CHUNK", "16")
  n_gen = 8
  outs = {}
  for mixed in (False, True):
    monkeypatch.setenv("XOT_TPU_MIXED_TICK", "1" if mixed else "0")
    server = BatchedServer(_engine(), n_slots=2, chunk=4, lookahead=True)
    calls: list = []
    _spy_mixed(server, calls, poison=not mixed)
    streams: dict[str, list] = {}

    async def run(server=server):
      def emit(rid, toks, finished):
        streams.setdefault(rid, []).extend(toks)

      first = asyncio.Event()

      def emit_first(rid, toks, finished):
        emit(rid, toks, finished)
        if toks:
          first.set()

      async def submit(rid, prompt, prio, em, max_tokens):
        return await server.submit(rid, np.asarray(prompt, np.int32), max_tokens=max_tokens, temp=0.0, top_k=35, eos_ids=(), emit=em, priority=prio)

      # Two residents fill the pool: one interactive survivor, one
      # batch-class victim; the interactive long prompt then has no free
      # slot and preempts the victim at the admission boundary.
      t_a = asyncio.ensure_future(submit("ra", [3, 25, 9], "interactive", emit_first, 24))
      t_b = asyncio.ensure_future(submit("rb", [7, 1, 88], "batch", emit, 24))
      await first.wait()
      out_c = await submit("rc", LONG, "interactive", emit, n_gen)
      return [await t_a, await t_b, out_c]

    outs[mixed] = asyncio.run(run())
    for rid, o in zip(("ra", "rb", "rc"), outs[mixed]):
      assert streams[rid] == o
    server.shutdown()
    if mixed:
      assert calls, "the preempting request's prefill never rode a mixed tick"
  assert outs[True] == outs[False]
  # Every stream equals its solo reference — including the preempted-and-
  # resumed batch row (resume identity holds through the mixed schedule).
  assert outs[True][0] == _single_row_reference(PARAMS, SHARD, [3, 25, 9], 23)
  assert outs[True][1] == _single_row_reference(PARAMS, SHARD, [7, 1, 88], 23)
  assert outs[True][2] == _single_row_reference(PARAMS, SHARD, LONG, n_gen - 1)


def test_mixed_budget_respected_and_no_starvation(monkeypatch):
  """Tick-planner property pin: under decode saturation a staged prefill
  advances monotonically (never starves), every slice stays within the
  policy budget, and the resident decode rows keep emitting between the
  prefill's start and its first token (prefill never starves decode)."""
  monkeypatch.setenv("XOT_TPU_PAGED", "1")
  monkeypatch.setenv("XOT_TPU_PAGE_SIZE", "16")
  monkeypatch.setenv("XOT_TPU_PREFILL_CHUNK", "32")
  monkeypatch.setenv("XOT_TPU_MIXED_TICK", "1")
  server = BatchedServer(_engine(), n_slots=3, chunk=4, lookahead=True)
  calls: list = []
  _spy_mixed(server, calls)
  resident_during_prefill = {"n": 0}
  long_first: dict = {}

  async def run():
    def emit_resident(rid, toks, finished):
      if toks and not long_first:
        resident_during_prefill["n"] += len(toks)

    def emit_long(rid, toks, finished):
      if toks and not long_first:
        long_first["t"] = True

    first = asyncio.Event()

    def emit_r0(rid, toks, finished):
      emit_resident(rid, toks, finished)
      if toks:
        first.set()

    # Two residents saturate decode with a long budget; the third slot is
    # the staged prefill's row.
    t0 = asyncio.ensure_future(server.submit("d0", np.asarray([3, 25, 9], np.int32), max_tokens=48, temp=0.0, top_k=35, eos_ids=(), emit=emit_r0))
    t1 = asyncio.ensure_future(server.submit("d1", np.asarray([9, 9, 1], np.int32), max_tokens=48, temp=0.0, top_k=35, eos_ids=(), emit=emit_resident))
    await first.wait()
    tl = asyncio.ensure_future(server.submit("long", np.asarray(LONG, np.int32), max_tokens=4, temp=0.0, top_k=35, eos_ids=(), emit=emit_long))
    return await asyncio.gather(t0, t1, tl)

  outs = asyncio.run(run())
  server.shutdown()
  assert [len(o) for o in outs] == [48, 48, 4]
  # Budget: burn is unmeasured and residents > 0 ⇒ cap/2 = 16 every tick.
  assert calls, "saturated decode starved the staged prefill out of mixed ticks"
  assert all(0 < e - s <= 16 for s, e in calls)
  # Progress in BOTH directions: the prefill's slices advance monotonically
  # tick over tick, and the residents kept emitting during the prefill span.
  assert all(b[0] >= a[1] for a, b in zip(calls, calls[1:])), calls
  assert resident_during_prefill["n"] > 0


def test_deadline_estimator_uses_measured_drain(monkeypatch):
  """ISSUE 14 satellite: the deadline estimator stops modeling queue drain
  as serial TTFT-per-waiter once a measured admission cadence exists (under
  mixed ticks prefill overlaps decode, so the serial model over-sheds); the
  serial model stays the cold-start fallback and the floor never rises."""
  from xotorch_support_jetson_tpu.inference.qos import QosConfig, QosPolicy

  class _Reg:
    def quantile(self, name, q, labels=None):
      return {"ttft_seconds": 2.0, "itl_seconds": 0.01}.get(name)

  now = {"t": 100.0}
  pol = QosPolicy(QosConfig(), clock=lambda: now["t"], registry=_Reg())
  monkeypatch.setenv("XOT_TPU_MIXED_TICK", "1")
  # Cold: serial model — 4 waiters / 2 slots at 2 s TTFT ⇒ 4 s drain.
  serial = pol.estimate_completion_ms(queue_depth=4, n_slots=2, max_tokens=10)
  assert serial == pytest.approx(4000.0 + 2000.0 + 100.0)
  # Measured cadence: admissions every 100 ms while work was waiting.
  for _ in range(6):
    now["t"] += 0.1
    pol.note_admission(waiting=3)
  assert pol.measured_drain_ms(4) == pytest.approx(400.0, rel=0.05)
  est = pol.estimate_completion_ms(queue_depth=4, n_slots=2, max_tokens=10)
  assert est == pytest.approx(400.0 + 2000.0 + 100.0, rel=0.05)
  assert est < serial  # the over-eager shed margin is gone
  # BATCHED admissions (K rows in one boundary pass, microseconds apart)
  # are one boundary of evidence, not K: the inter-boundary gap splits over
  # the pass size — K near-zero intra-pass gaps must not drag the EWMA
  # toward 0 (that would flip the estimator to under-shedding).
  for _ in range(12):  # boundaries every 400 ms admitting 4 each
    now["t"] += 0.4
    pol.note_admission(waiting=5)
    for _ in range(3):
      now["t"] += 1e-5
      pol.note_admission(waiting=5)
  assert pol.measured_drain_ms(1) == pytest.approx(100.0, rel=0.1)  # 400 ms / 4 rows
  # A SLOW boundary pass (each admission doing milliseconds of host work —
  # page restores, validation) still groups by the caller's pass id: the
  # wall-clock heuristic alone would misread the intra-pass gaps as
  # separate boundaries and drag the EWMA toward the per-admission host
  # cost (under-shedding).
  for p in range(8):
    now["t"] += 0.4
    pol.note_admission(waiting=5, pass_id=p)
    for _ in range(3):
      now["t"] += 0.005  # 5 ms of host work per admission, same pass
      pol.note_admission(waiting=5, pass_id=p)
  assert pol.measured_drain_ms(1) == pytest.approx(104.0, rel=0.1)  # ≈415 ms / 4 rows
  # An admission off an idle queue drops the anchor: the idle gap that
  # follows must not count as drain evidence.
  now["t"] += 30.0
  pol.note_admission(waiting=0)
  now["t"] += 0.1
  pol.note_admission(waiting=2)  # fresh anchor — no 30 s gap recorded
  assert pol.measured_drain_ms(4) < 1000.0
  # Mixed ticks off ⇒ the serial model stands (alternating really is serial).
  monkeypatch.setenv("XOT_TPU_MIXED_TICK", "0")
  assert pol.estimate_completion_ms(queue_depth=4, n_slots=2, max_tokens=10) == pytest.approx(serial)


def test_mixed_metrics_families(monkeypatch):
  """The mixed dispatch's attribution split: ``mixed_tick_seconds`` gets the
  fused dispatch (decode_chunk_seconds must NOT — one dispatch, one home)
  and ``sched_tick_prefill_tokens_total`` counts exactly the slice tokens."""
  from xotorch_support_jetson_tpu.utils.metrics import metrics, snapshot_delta

  monkeypatch.setenv("XOT_TPU_PAGED", "1")
  monkeypatch.setenv("XOT_TPU_PAGE_SIZE", "16")
  monkeypatch.setenv("XOT_TPU_PREFILL_CHUNK", "16")
  monkeypatch.setenv("XOT_TPU_MIXED_TICK", "1")
  server = BatchedServer(_engine(), n_slots=4, chunk=4)
  calls: list = []
  _spy_mixed(server, calls)
  before = metrics.snapshot()
  _serve(server, PROMPTS, 8)
  server.shutdown()
  delta = snapshot_delta(before, metrics.snapshot())
  assert calls
  sliced = sum(e - s for s, e in calls)
  assert delta["counters"].get("sched_tick_prefill_tokens_total") == sliced
  mixed_hist = delta["histograms"].get("mixed_tick_seconds")
  assert mixed_hist and sum(mixed_hist["counts"]) == len(calls)
  assert metrics.gauge_value("mixed_budget_tokens") == 16
