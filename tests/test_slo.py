"""SLO engine, goodput accounting, terminal-stage invariant, and cluster
SLO/bundle assembly (ISSUE 9).

Covers: objective env overrides; bucket-edge threshold semantics; the
multi-window burn-rate math against histogram fixtures; exact cluster merge
(sum of raw counts, never average of averages); scheduler goodput
accounting (within-SLO vs violating, preserved across the token paths);
the XOT_TPU_SLO=0 byte-identical off switch; the every-request-reaches-
exactly-one-terminal invariant across completion, refusal, preempt-resume,
and chaos-injected paths; and the two-node gRPC cluster SLO pull + bundle
assembly with a killed peer yielding an annotated-partial bundle without a
hang.
"""

import asyncio
import time

import numpy as np
import pytest

from xotorch_support_jetson_tpu.inference.engine import ServerOverloadedError
from xotorch_support_jetson_tpu.orchestration import slo
from xotorch_support_jetson_tpu.orchestration.flightrec import flightrec
from xotorch_support_jetson_tpu.orchestration.slo import (
  SloEngine,
  hist_over_threshold,
  merge_slo_reports,
  objectives,
  slo_engine,
)
from xotorch_support_jetson_tpu.orchestration.tracing import TERMINAL_STAGES, tracer
from xotorch_support_jetson_tpu.utils.metrics import Metrics, metrics as gm, snapshot_delta


# ------------------------------------------------------------ objectives/env


def test_objectives_defaults_and_env_overrides(monkeypatch):
  assert objectives("interactive")["ttft_p95_ms"] == 500.0
  assert objectives("batch")["availability"] == 0.99
  assert objectives("no-such-class") == objectives("standard")
  monkeypatch.setenv("XOT_TPU_SLO_INTERACTIVE_TTFT_P95_MS", "250")
  monkeypatch.setenv("XOT_TPU_SLO_INTERACTIVE_AVAILABILITY", "0.9999")
  obj = objectives("interactive")
  assert obj["ttft_p95_ms"] == 250.0 and obj["availability"] == 0.9999
  # A nonsense 1.0 target would make the budget zero — clamped below 1.
  monkeypatch.setenv("XOT_TPU_SLO_INTERACTIVE_AVAILABILITY", "1.0")
  assert objectives("interactive")["availability"] < 1.0


def test_slo_off_switch(monkeypatch):
  monkeypatch.setenv("XOT_TPU_SLO", "0")
  assert slo.slo_enabled() is False
  monkeypatch.delenv("XOT_TPU_SLO")
  assert slo.slo_enabled() is True


# ------------------------------------------------------- threshold semantics


def test_hist_over_threshold_bucket_edge_rounding():
  m = Metrics()
  m.observe_hist("h", 0.1, n=90)
  m.observe_hist("h", 1.0, n=10)
  h = m.snapshot()["histograms"]["h"]
  # Exact bucket edge: 0.5 — the 0.1s are under, the 1.0s violate.
  assert hist_over_threshold(h, 0.5) == (10, 100)
  # Non-edge threshold rounds DOWN to the last edge <= it (0.6 -> 0.5):
  # an 0.55 observation counts as violating — conservative toward alerting.
  m2 = Metrics()
  m2.observe_hist("h", 0.55, n=5)
  m2.observe_hist("h", 0.3, n=5)
  h2 = m2.snapshot()["histograms"]["h"]
  assert hist_over_threshold(h2, 0.6) == (5, 10)
  # Threshold above the ladder: only +Inf-bucket entries violate.
  assert hist_over_threshold(h, 60.0) == (0, 100)


# ------------------------------------------------------- window burn math


def _fixture_snapshot():
  """100 interactive requests: 90 TTFTs at 100 ms, 10 at 1 s (threshold
  500 ms -> 10% violations); availability 99 good / 1 bad; 1000 tokens of
  which 800 good."""
  m = Metrics()
  m.observe_hist("qos_ttft_seconds", 0.1, n=90, labels={"class": "interactive"})
  m.observe_hist("qos_ttft_seconds", 1.0, n=10, labels={"class": "interactive"})
  m.inc("slo_requests_good_total", 99, labels={"class": "interactive"})
  m.inc("slo_requests_bad_total", 1, labels={"class": "interactive", "reason": "shed"})
  m.inc("slo_tokens_total", 1000, labels={"class": "interactive", "tenant": "t1"})
  m.inc("slo_good_tokens_total", 800, labels={"class": "interactive", "tenant": "t1"})
  return m.snapshot()


def test_window_burn_rates_against_fixture():
  engine = SloEngine(tick_s=1.0, windows_s=(60.0,))
  now = time.time()
  engine._ring.append((now - 120.0, Metrics().snapshot()))  # empty base, 120 s old
  stats = engine._window_stats(now, _fixture_snapshot(), 60.0)
  w = stats["classes"]["interactive"]
  # TTFT p95 objective (500 ms): 10/100 over -> burn = 0.10 / 0.05 = 2.
  assert w["ttft"] == {"violations": 10, "total": 100, "burn_rate": pytest.approx(2.0)}
  # Availability 0.999: bad fraction 1% vs budget 0.1% -> burn 10.
  assert w["availability"]["good"] == 99 and w["availability"]["bad"] == 1
  assert w["availability"]["burn_rate"] == pytest.approx(10.0)
  # No ITL data -> burn None, never 0 (unknown != healthy).
  assert w["itl"]["burn_rate"] is None
  # Goodput rate over the REAL elapsed span (120 s), not the window label.
  assert w["goodput"]["good_tok_s"] == pytest.approx(800 / 120.0, rel=1e-3)
  # Untouched class: zero counts, burns None.
  b = stats["classes"]["batch"]
  assert b["availability"]["burn_rate"] is None and b["ttft"]["total"] == 0


def test_report_attainment_and_no_history():
  engine = SloEngine(tick_s=1.0, windows_s=(60.0,))
  # No ring at all: a young engine reports zero-traffic windows, attainment None.
  rep = engine._report_locked_free(time.time(), Metrics().snapshot())
  assert rep["classes"]["interactive"]["attainment"] is None
  now = time.time()
  engine._ring.append((now - 90.0, Metrics().snapshot()))
  rep = engine._report_locked_free(now, _fixture_snapshot())
  entry = rep["classes"]["interactive"]
  # Attainment = worst objective over the longest window: min(ttft 0.90,
  # availability 0.99) = 0.90.
  assert entry["attainment"] == pytest.approx(0.90)
  assert entry["goodput_cum"] == {"tokens": 1000, "good_tokens": 800}


def test_tick_exports_gauges_and_is_rate_limited(monkeypatch):
  monkeypatch.setenv("XOT_TPU_BUNDLE_MIN_INTERVAL_S", "999999")
  engine = SloEngine(tick_s=30.0, windows_s=(60.0,))
  engine._ring.append((time.time() - 90.0, Metrics().snapshot()))
  assert engine.maybe_tick() is True
  assert engine.maybe_tick() is False  # inside the tick interval
  text = gm.render_prometheus()
  assert 'xot_tpu_slo_burn_rate{class="interactive",window="60s"}' in text
  assert 'xot_tpu_slo_attainment{class="batch"}' in text
  assert 'xot_tpu_goodput_tok_s{class="standard"}' in text


def test_disabled_engine_reports_and_ticks_nothing(monkeypatch):
  monkeypatch.setenv("XOT_TPU_SLO", "0")
  engine = SloEngine(tick_s=0.001, windows_s=(60.0,))
  assert engine.maybe_tick() is False
  assert len(engine._ring) == 0
  assert engine.report() == {"scope": "local", "enabled": False}


# ------------------------------------------------------------- cluster merge


def _mini_report(node_id, violations, total, good, bad):
  burn = (violations / total / 0.05) if total else None
  n = good + bad
  return {
    "enabled": True,
    "node_id": node_id,
    "windows_s": [300],
    "classes": {
      "interactive": {
        "objectives": objectives("interactive"),
        "windows": {"300": {
          "elapsed_s": 300.0,
          "ttft": {"violations": violations, "total": total, "burn_rate": burn},
          "itl": {"violations": 0, "total": 0, "burn_rate": None},
          "availability": {"good": good, "bad": bad, "burn_rate": (bad / n / 0.001) if n else None},
          "goodput": {"tokens": total * 10, "good_tokens": total * 8, "good_tok_s": None},
        }},
        "goodput_cum": {"tokens": total * 10, "good_tokens": total * 8},
      }
    },
  }


def test_merge_is_exact_not_average_of_averages():
  # Node A: 10/100 over (burn 2.0). Node B: 0/900 over (burn 0.0).
  # Average of burns would say 1.0; the exact cluster burn is
  # (10/1000)/0.05 = 0.2.
  merged = merge_slo_reports([_mini_report("a", 10, 100, 99, 1), _mini_report("b", 0, 900, 900, 0)])
  w = merged["classes"]["interactive"]["windows"]["300"]
  assert w["ttft"] == {"violations": 10, "total": 1000, "burn_rate": pytest.approx(0.2)}
  assert w["availability"]["good"] == 999 and w["availability"]["bad"] == 1
  assert w["availability"]["burn_rate"] == pytest.approx(1 / 1000 / 0.001)
  assert merged["nodes"] == ["a", "b"] and merged["nodes_reporting"] == 2
  assert merged["classes"]["interactive"]["goodput_cum"] == {"tokens": 10000, "good_tokens": 8000}
  # Disabled nodes are counted but contribute nothing.
  merged2 = merge_slo_reports([_mini_report("a", 10, 100, 99, 1), {"enabled": False, "node_id": "off"}])
  assert merged2["nodes_reporting"] == 2
  assert merged2["classes"]["interactive"]["windows"]["300"]["ttft"]["total"] == 100


# ------------------------------------------------- snapshot_delta semantics


def test_snapshot_delta_semantics():
  m = Metrics()
  m.inc("c", 5)
  m.inc("lc", 2, labels={"k": "v"})
  m.set_gauge("g", 10)
  m.observe_hist("h", 0.1, n=3)
  s1 = m.snapshot()
  m.inc("c", 2)
  m.inc("lc", 1, labels={"k": "v"})
  m.set_gauge("g", 4)
  m.observe_hist("h", 0.3, n=2)
  s2 = m.snapshot()
  d = snapshot_delta(s1, s2)
  assert d["counters"]["c"] == 2.0
  assert dict((tuple(map(tuple, k)), v) for k, v in d["labeled_counters"]["lc"])[(("k", "v"),)] == 1.0
  assert d["gauges"]["g"] == 4  # gauges are levels: current value, not delta
  assert sum(d["histograms"]["h"]["counts"]) == 2
  # Shrunk counter (registry restart): floored at zero, never negative.
  assert snapshot_delta(s2, s1)["counters"]["c"] == 0.0
  # Incompatible prev ladder: cur passes through as-is.
  m3 = Metrics()
  m3.observe_hist("h", 2, n=4, buckets=(1.0, 4.0))
  d2 = snapshot_delta(s1, m3.snapshot())
  assert sum(d2["histograms"]["h"]["counts"]) == 4


# ------------------------------------------- scheduler goodput accounting


def _objectives_wide(monkeypatch):
  """CPU tiny-model runs include compile time — keep the latency objectives
  out of the way so 'good' is deterministic."""
  monkeypatch.setenv("XOT_TPU_SLO_STANDARD_TTFT_P95_MS", "600000")
  monkeypatch.setenv("XOT_TPU_SLO_STANDARD_ITL_P99_MS", "600000")


def _drive_tiny(rid, n=4):
  from tests.test_observability import _tiny_batched_server

  server = _tiny_batched_server()
  out = {}

  async def run():
    out["tokens"] = await server.submit(
      rid, np.asarray([5, 6, 7], np.int32), max_tokens=n, temp=0.0, top_k=35, eos_ids=(), emit=lambda *_: None,
    )

  asyncio.run(run())
  server.shutdown()
  return out["tokens"]


def test_scheduler_goodput_within_slo(monkeypatch):
  _objectives_wide(monkeypatch)
  labels = {"class": "standard", "tenant": "default"}
  before_tok = gm.counter_value("slo_tokens_total", labels=labels)
  before_good = gm.counter_value("slo_good_tokens_total", labels=labels)
  before_ok = gm.counter_value("slo_requests_good_total", labels={"class": "standard"})
  toks = _drive_tiny("slo-good", n=4)
  assert len(toks) == 4
  assert gm.counter_value("slo_tokens_total", labels=labels) == before_tok + 4
  assert gm.counter_value("slo_good_tokens_total", labels=labels) == before_good + 4
  # Availability's GOOD event belongs to the API token choke point (the
  # layer every serving path streams through), NOT the scheduler — a
  # scheduler-only drive must not move it.
  assert gm.counter_value("slo_requests_good_total", labels={"class": "standard"}) == before_ok
  # Per-class TTFT/ITL landed in the labeled families.
  assert gm.hist_count("qos_ttft_seconds", labels={"class": "standard"}) >= 1
  assert gm.hist_count("qos_itl_seconds", labels={"class": "standard"}) >= 1


def test_scheduler_goodput_ttft_violation_counts_total_not_good(monkeypatch):
  monkeypatch.setenv("XOT_TPU_SLO_STANDARD_TTFT_P95_MS", "0.000001")
  monkeypatch.setenv("XOT_TPU_SLO_STANDARD_ITL_P99_MS", "600000")
  labels = {"class": "standard", "tenant": "default"}
  before_tok = gm.counter_value("slo_tokens_total", labels=labels)
  before_good = gm.counter_value("slo_good_tokens_total", labels=labels)
  _drive_tiny("slo-viol", n=4)
  # Delivered tokens all count; none are goodput (the request violated its
  # TTFT objective — latency is goodput's concern, not availability's).
  assert gm.counter_value("slo_tokens_total", labels=labels) == before_tok + 4
  assert gm.counter_value("slo_good_tokens_total", labels=labels) == before_good


def test_slo_off_is_byte_identical(monkeypatch):
  """The acceptance pin: XOT_TPU_SLO=0 XOT_TPU_FLIGHTREC=0 leaves the
  serving path byte-identical — same token stream, zero SLO series moved,
  zero flight events recorded."""
  reference = _drive_tiny("slo-ref", n=4)
  monkeypatch.setenv("XOT_TPU_SLO", "0")
  monkeypatch.setenv("XOT_TPU_FLIGHTREC", "0")
  before = gm.snapshot()
  ring_before = len(flightrec)
  toks = _drive_tiny("slo-off", n=4)
  delta = snapshot_delta(before, gm.snapshot())
  assert toks == reference  # serving output identical
  assert len(flightrec) == ring_before  # recorder untouched
  # NO slo/qos-class series moved: the hooks never ran.
  for name in ("slo_tokens_total", "slo_good_tokens_total", "slo_requests_good_total", "slo_requests_bad_total"):
    assert sum(v for _, v in (delta.get("labeled_counters") or {}).get(name, [])) == 0, name
  for name in ("qos_ttft_seconds", "qos_itl_seconds"):
    series = (delta.get("labeled_histograms") or {}).get(name, [])
    assert sum(sum(h["counts"]) for _, h in series) == 0, name


# --------------------------------------------------- terminal-stage invariant


def _terminal_events(rid):
  tl = tracer.timeline(rid)
  assert tl is not None, rid
  return tl, [e for e in tl["events"] if e["stage"] in TERMINAL_STAGES]


def _qos_server(**kw):
  import jax

  from xotorch_support_jetson_tpu.inference.batch_scheduler import BatchedServer
  from xotorch_support_jetson_tpu.inference.jax_engine import JaxShardedInferenceEngine
  from xotorch_support_jetson_tpu.models.config import tiny_test_config
  from xotorch_support_jetson_tpu.models.decoder import full_model_params

  cfg = tiny_test_config(n_layers=2, max_seq_len=128)
  params, shard = full_model_params(jax.random.PRNGKey(0), cfg, "m")
  engine = JaxShardedInferenceEngine(use_local_mesh=False)
  engine.load_test_model(shard, cfg, params)
  return BatchedServer(engine, n_slots=1, chunk=2, qos=True, **kw)


def test_terminal_invariant_complete_via_node():
  """Normal completion through the node serving path ends terminal
  'complete' — set by end_request, exactly once."""
  from xotorch_support_jetson_tpu.inference.dummy_engine import DummyInferenceEngine
  from xotorch_support_jetson_tpu.orchestration.node import Node
  from xotorch_support_jetson_tpu.registry import build_base_shard
  from xotorch_support_jetson_tpu.topology.partitioning import RingMemoryWeightedPartitioningStrategy
  from tests_support_stubs import NoDiscovery, StubServer

  async def run():
    node = Node(
      "term-node", StubServer(), DummyInferenceEngine(), NoDiscovery(), None,
      RingMemoryWeightedPartitioningStrategy(), max_generate_tokens=50,
    )
    await node.start()
    shard = build_base_shard("dummy", "DummyInferenceEngine")
    done = asyncio.Event()
    node.on_token.register("term").on_next(lambda rid, toks, fin: done.set() if fin else None)
    await node.process_prompt(shard, "aaaa", "term-ok")
    await asyncio.wait_for(done.wait(), timeout=30)
    await node.stop()

  asyncio.run(run())
  tl, terms = _terminal_events("term-ok")
  assert tl["finished"] and tl["terminal"] == "complete"
  assert terms == []  # 'complete' is the classification, not a refusal event


@pytest.mark.parametrize("path", ["rejected", "shed_overload", "shed_deadline", "rate_limited"])
def test_terminal_invariant_refusal_paths(path, monkeypatch):
  """Every refusal path stamps EXACTLY ONE terminal refusal stage and
  finishes the timeline — the goodput/availability denominator's contract."""
  server = _qos_server(max_queue=1)
  rid = f"term-{path}"

  async def run():
    streams = {}

    def emit(r, toks, fin):
      streams.setdefault(r, []).extend(toks)

    # A long-running resident occupies the single slot; a queued waiter
    # fills the queue for the overload paths.
    bg = asyncio.create_task(server.submit("bg-" + path, np.asarray([3, 25, 9], np.int32), max_tokens=30, temp=0.0, top_k=35, eos_ids=(), emit=emit, priority="standard", tenant="bulk"))
    while not any(streams.get("bg-" + path) or []):
      await asyncio.sleep(0.01)
    waiter = None
    if path in ("rejected", "shed_overload"):
      # Fill the 1-deep queue. For the shed path the waiter is strictly
      # lower priority than the arrival (it becomes the victim); for the
      # reject path it is the SAME class, so nothing outranked waits and
      # the new arrival itself is rejected.
      waiter = asyncio.create_task(server.submit("w-" + path, np.asarray([4, 4, 4], np.int32), max_tokens=4, temp=0.0, top_k=35, eos_ids=(), emit=emit, priority="batch" if path == "shed_overload" else "interactive", tenant="bulk"))
      while server.queue.qsize() == 0:
        await asyncio.sleep(0.01)
    if path == "shed_deadline":
      monkeypatch.setattr(server.qos, "estimate_completion_ms", lambda **kw: 1e9)
    if path == "rate_limited":
      def deny(tenant, toks):
        raise ServerOverloadedError("rate limited (test)")
      monkeypatch.setattr(server.qos, "check_rate", deny)
    submit = server.submit(
      rid, np.asarray([9, 9, 9], np.int32), max_tokens=4, temp=0.0, top_k=35, eos_ids=(),
      emit=emit, priority="interactive" if path in ("rejected", "shed_overload") else "standard",
      tenant="vip", deadline_ms=5.0 if path == "shed_deadline" else None,
    )
    if path == "shed_overload":
      await submit  # the interactive arrival displaces the queued batch waiter
      with pytest.raises(ServerOverloadedError):
        await waiter
    else:
      with pytest.raises(Exception):
        await submit
      if waiter is not None:
        await waiter  # the same-class waiter was NOT displaced; it completes
    await bg

  asyncio.run(run())
  server.shutdown()
  victim = {"rejected": rid, "shed_overload": "w-" + path, "shed_deadline": rid, "rate_limited": rid}[path]
  expected = {"rejected": "rejected", "shed_overload": "shed", "shed_deadline": "shed", "rate_limited": "rate_limited"}[path]
  tl, terms = _terminal_events(victim)
  assert tl["finished"] and tl["terminal"] == expected
  assert len(terms) == 1 and terms[0]["stage"] == expected


def test_terminal_invariant_preempt_resume_single_complete():
  """A preempted-then-resumed request crosses preempt/resume stages but
  still terminates EXACTLY ONCE as complete; goodput judges the FIRST
  incarnation's TTFT (slo_ttft_s survives the preemption)."""
  from xotorch_support_jetson_tpu.inference.qos import QosConfig, QosPolicy

  import jax

  from xotorch_support_jetson_tpu.inference.batch_scheduler import BatchedServer
  from xotorch_support_jetson_tpu.inference.jax_engine import JaxShardedInferenceEngine
  from xotorch_support_jetson_tpu.models.config import tiny_test_config
  from xotorch_support_jetson_tpu.models.decoder import full_model_params

  cfg = tiny_test_config(n_layers=2, max_seq_len=128)
  params, shard = full_model_params(jax.random.PRNGKey(0), cfg, "m")
  engine = JaxShardedInferenceEngine(use_local_mesh=False)
  engine.load_test_model(shard, cfg, params)
  server = BatchedServer(engine, n_slots=1, chunk=2, qos=QosPolicy(QosConfig(aging_s=10_000.0)))

  async def run():
    started = asyncio.Event()
    streams = {}

    def emit(r, toks, fin):
      streams.setdefault(r, []).extend(toks)
      if r == "bg" and len(streams["bg"]) >= 4:
        started.set()

    bg = asyncio.create_task(server.submit("bg", np.asarray([3, 25, 9], np.int32), max_tokens=24, temp=0.0, top_k=35, eos_ids=(), emit=emit, priority="batch", tenant="bulk"))
    await asyncio.wait_for(started.wait(), timeout=60)
    await asyncio.wait_for(
      server.submit("vip", np.asarray([7, 1, 88], np.int32), max_tokens=4, temp=0.0, top_k=35, eos_ids=(), emit=emit, priority="interactive", tenant="vip"),
      timeout=60,
    )
    await asyncio.wait_for(bg, timeout=60)

  asyncio.run(run())
  server.shutdown()
  # The preempted request's timeline carries the preempted stage but no
  # refusal terminal; its availability classification is 'complete'-bound
  # (end_request runs at the node/API layer — at scheduler level no refusal
  # stage may have fired).
  tl = tracer.timeline("bg")
  stages = [e["stage"] for e in tl["events"]]
  assert "preempted" in stages
  assert [e for e in tl["events"] if e["stage"] in TERMINAL_STAGES] == []
  assert tl["terminal"] is None  # the API layer's end_request classifies it
  tracer.end_request("bg")
  assert tracer.timeline("bg")["terminal"] == "complete"


def test_terminal_invariant_chaos_kill_path():
  """Chaos-injected node kill mid-decode: the replay completes the request
  token-identically (PR 8) and the terminal classification is still exactly
  one 'complete' — with the replay recorded in the flight ring (ISSUE 9:
  the forensics of the acceptance scenario)."""
  from xotorch_support_jetson_tpu.networking.faults import chaos
  from xotorch_support_jetson_tpu.networking.retry import breakers, peer_health
  from tests.test_chaos import FAULT_FREE_TOKENS, _drive_ring_request
  from tests.test_networking import _make_cluster

  chaos.clear()
  breakers.reset()
  peer_health.reset()

  async def run():
    nodes = await _make_cluster(2)
    killed = []

    def maybe_kill(collected):
      if not killed and collected:
        killed.append(True)
        chaos.kill("node1")
        asyncio.ensure_future(nodes[1].server.stop())

    try:
      collected = await _drive_ring_request(nodes, "slo-chaos-kill", on_tokens=maybe_kill)
      assert killed and collected == FAULT_FREE_TOKENS
    finally:
      chaos.clear()
      breakers.reset()
      peer_health.reset()
      for n in nodes:
        await n.stop()

  asyncio.run(run())
  tl, terms = _terminal_events("slo-chaos-kill")
  assert tl["finished"] and tl["terminal"] == "complete"
  assert terms == []
  # The flight ring holds the replay in causal order before the completion.
  evs = flightrec.query(request_id="slo-chaos-kill", limit=100)
  types = [e["type"] for e in evs]
  assert "replay" in types and "complete" in types
  assert types.index("replay") < types.index("complete")


# ------------------------------------------------------ cluster SLO + bundle


def test_cluster_slo_and_bundle_on_real_grpc_cluster(monkeypatch, tmp_path):
  """The acceptance fixture: a REAL two-node gRPC cluster. /v1/slo's
  cluster scope merges both nodes' reports pulled over the opaque-status
  channel; a bundle captures both peers' parts; killing a peer yields an
  annotated-partial bundle WITHOUT a hang."""
  monkeypatch.setenv("XOT_TPU_BUNDLE_DIR", str(tmp_path))
  from tests.test_chaos import _drive_ring_request
  from tests.test_networking import _make_cluster

  out = {}

  async def run():
    nodes = await _make_cluster(2)
    try:
      # Serve one real request over the ring so timelines/counters move.
      await _drive_ring_request(nodes, "slo-cluster-req")
      # Give the (shared, in-process) engine a window base so burn rates
      # compute over real counter deltas.
      slo_engine.reset()
      slo_engine._ring.append((time.time() - 400.0, Metrics().snapshot()))
      slo.note_good("interactive")
      slo.note_bad("interactive", "shed")
      reports = await nodes[0].collect_cluster_slo()
      out["reports"] = reports
      out["merged"] = nodes[0].merged_cluster_slo(reports)
      out["local"] = slo_engine.report(node_id="node0")
      bundle = await nodes[0].collect_cluster_bundle(reason="drill", timeout=5.0)
      out["bundle"] = bundle
      # Kill the peer: its server goes down hard.
      await nodes[1].stop()
      t0 = time.monotonic()
      out["partial"] = await nodes[0].collect_cluster_bundle(reason="dead-peer", timeout=2.0)
      out["partial_elapsed"] = time.monotonic() - t0
    finally:
      for n in nodes:
        try:
          await n.stop()
        except Exception:
          pass

  asyncio.run(run())
  # One report per peer, carrying the peer's node id.
  assert [r.get("node_id") for r in out["reports"]] == ["node1"]
  merged = out["merged"]
  assert merged["scope"] == "cluster" and merged["nodes_reporting"] == 2
  assert set(merged["nodes"]) == {"node0", "node1"}
  # Merged counts are the SUM of both nodes' raw counts (shared in-process
  # registry -> exactly 2x the local report), and the burn is recomputed
  # from the sums.
  wk = str(int(min(slo_engine.windows)))
  local_avail = out["local"]["classes"]["interactive"]["windows"][wk]["availability"]
  merged_avail = merged["classes"]["interactive"]["windows"][wk]["availability"]
  assert merged_avail["good"] == 2 * local_avail["good"]
  assert merged_avail["bad"] == 2 * local_avail["bad"]
  assert merged_avail["bad"] >= 1 and merged_avail["burn_rate"] > 0  # the availability burn is visible
  # Full bundle: both peers answered, each part carries its flight events.
  bundle = out["bundle"]
  assert bundle["nodes_reporting"] == 2 and bundle["nodes_unreachable"] == []
  assert {p.get("node_id") for p in bundle["parts"]} == {"node0", "node1"}
  assert all("events" in p and "breakers" in p for p in bundle["parts"])
  # Killed peer: annotated as unreachable, and the call stayed bounded.
  partial = out["partial"]
  unreachable = partial["nodes_unreachable"]
  assert [u["node_id"] for u in unreachable] == ["node1"] and unreachable[0]["unreachable"] is True
  assert partial["nodes_reporting"] == 1
  assert out["partial_elapsed"] < 10.0  # never waits out a dead peer


@pytest.mark.asyncio
async def test_slo_endpoint_local_and_disabled(monkeypatch):
  from aiohttp.test_utils import TestClient, TestServer

  from xotorch_support_jetson_tpu.api.chatgpt_api import ChatGPTAPI
  from xotorch_support_jetson_tpu.inference.dummy_engine import DummyInferenceEngine
  from xotorch_support_jetson_tpu.orchestration.node import Node
  from xotorch_support_jetson_tpu.topology.partitioning import RingMemoryWeightedPartitioningStrategy
  from tests_support_stubs import NoDiscovery, StubServer

  node = Node(
    "slo-api", StubServer(), DummyInferenceEngine(), NoDiscovery(), None,
    RingMemoryWeightedPartitioningStrategy(), max_generate_tokens=50,
  )
  await node.start()
  api = ChatGPTAPI(node, "DummyInferenceEngine", response_timeout=30, default_model="dummy")
  client = TestClient(TestServer(api.app))
  await client.start_server()
  try:
    # A served request counts ONE availability good event at the API token
    # choke point — every serving mode streams through it (the plain/ring
    # path included, which never touches the batched scheduler's hooks).
    before_ok = gm.counter_value("slo_requests_good_total", labels={"class": "standard"})
    resp = await client.post(
      "/v1/chat/completions",
      json={"model": "dummy", "messages": [{"role": "user", "content": "aaaa"}], "stream": False},
    )
    assert resp.status == 200
    assert gm.counter_value("slo_requests_good_total", labels={"class": "standard"}) == before_ok + 1
    resp = await client.get("/v1/slo")
    data = await resp.json()
    assert resp.status == 200 and data["enabled"] is True
    assert set(data["classes"]) == {"interactive", "standard", "batch"}
    for cls in data["classes"].values():
      assert set(cls["objectives"]) == {"ttft_p95_ms", "itl_p99_ms", "availability"}
    # Cluster scope with no peers: merged shape, one reporter.
    resp = await client.get("/v1/slo?scope=cluster")
    data = await resp.json()
    assert data["scope"] == "cluster" and data["nodes_reporting"] == 1
    monkeypatch.setenv("XOT_TPU_SLO", "0")
    resp = await client.get("/v1/slo")
    data = await resp.json()
    assert resp.status == 200 and data["enabled"] is False
  finally:
    await client.close()
    await node.stop()
