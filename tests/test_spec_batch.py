"""Batched speculative decoding as a first-class scheduler mode (ISSUE 7,
inference/batch_scheduler.py ``XOT_TPU_SPEC_BATCH``).

The correctness contract: with speculation ON, greedy batched output is
TOKEN-IDENTICAL to the plain batched program (which is itself pinned against
solo greedy decode) — for any draft, on both cache layouts, with the
lookahead pipeline on or off. Depth adapts PER ROW through the acceptance
EWMA (inference/paging.py ``spec_adapt_gamma``): an adversarial draft
collapses every row to gamma 0 and the scheduler falls back to the plain
chunk program instead of dragging the batch; ``XOT_TPU_SPEC_BATCH=0``
restores plain dispatches byte-for-byte.
"""

import asyncio

import jax
import numpy as np
import pytest

from tests.test_batched import _single_row_reference
from tests.test_lookahead import _serve
from xotorch_support_jetson_tpu.inference.batch_scheduler import BatchedServer
from xotorch_support_jetson_tpu.inference.jax_engine import JaxShardedInferenceEngine
from xotorch_support_jetson_tpu.models.config import tiny_test_config
from xotorch_support_jetson_tpu.models.decoder import full_model_params
from xotorch_support_jetson_tpu.models.quantize import quantize_params
from xotorch_support_jetson_tpu.utils.metrics import metrics as gm
from xotorch_support_jetson_tpu.utils.synthetic import peaked_echo_params

CFG = tiny_test_config(n_layers=2, max_seq_len=128, tied_embedding=True)
KEY = jax.random.PRNGKey(0)
PROMPTS = [[3, 25, 9], [7, 1, 88, 42, 5], [100], [9, 9, 9, 1]]


def _echo_engine(cfg=CFG, key=KEY):
  """Engine whose int8 self-draft ACCEPTS: the peaked echo model's draft
  agrees with the target almost always, so accepted runs actually happen."""
  params, shard = full_model_params(key, cfg, "m")
  params = peaked_echo_params(params)
  engine = JaxShardedInferenceEngine(use_local_mesh=False, spec_decode="int8")
  engine.load_test_model(shard, cfg, params)
  assert engine._draft_params is not None
  return engine, params, shard


def _random_engine(cfg=CFG, key=KEY):
  params, shard = full_model_params(key, cfg, "m")
  engine = JaxShardedInferenceEngine(use_local_mesh=False, spec_decode="int8")
  engine.load_test_model(shard, cfg, params)
  assert engine._draft_params is not None
  return engine, params, shard


def _spec_ab(engine, params, shard, prompts, n_gen, *, chunk=4, n_slots=4, cfg=CFG):
  """Serve the same prompts with speculation ON and OFF, each with lookahead
  ON and OFF; assert all four modes produce the identical, solo-reference
  greedy streams."""
  expected = [_single_row_reference(params, shard, p, n_gen - 1, cfg=cfg) for p in prompts]
  outs = {}
  for spec in (True, False):
    for la in (True, False):
      server = BatchedServer(engine, n_slots=n_slots, chunk=chunk, lookahead=la, spec_batch=spec)
      outs[(spec, la)], streams = _serve(server, prompts, n_gen)
      for o, s in zip(outs[(spec, la)], streams):
        assert s == o  # emitted stream matches the resolved result
      if spec:
        assert server.spec, "speculation should have resolved ON"
      server.shutdown()
  for mode, out in outs.items():
    assert out == expected, f"mode {mode} diverged from solo greedy: {out} != {expected}"
  return expected


def test_spec_batch_ab_paged_int8kv(monkeypatch):
  """A/B at the serving default (paged pool, int8-KV pages): spec×lookahead
  (4 modes) all token-identical to solo greedy — with a HIGH-acceptance
  draft, so accepted multi-token runs really flow through the variable
  advance."""
  monkeypatch.setenv("XOT_TPU_PAGED", "1")
  monkeypatch.setenv("XOT_TPU_KV_QUANT", "int8")
  monkeypatch.setenv("XOT_TPU_PAGE_SIZE", "16")
  engine, params, shard = _echo_engine()
  before = gm.counter_sum("spec_accepted_tokens_total")  # {proposer}-labeled since ISSUE 12
  _spec_ab(engine, params, shard, PROMPTS, 8)
  # The echo draft really accepted: multi-token advances happened.
  assert gm.counter_sum("spec_accepted_tokens_total") > before


def test_spec_batch_ab_paged_adversarial_draft(monkeypatch):
  """Same A/B with a RANDOM model (its int8 self-draft rarely agrees):
  identity must hold for any draft — acceptance only changes speed."""
  monkeypatch.setenv("XOT_TPU_PAGED", "1")
  monkeypatch.setenv("XOT_TPU_PAGE_SIZE", "16")
  engine, params, shard = _random_engine()
  _spec_ab(engine, params, shard, PROMPTS, 6)


def test_spec_batch_ab_dense(monkeypatch):
  """A/B on the dense slot layout: the spec program's verify pass runs
  through the ordinary slot-cache forward."""
  monkeypatch.setenv("XOT_TPU_PAGED", "0")
  engine, params, shard = _echo_engine()
  _spec_ab(engine, params, shard, PROMPTS, 8)


def test_spec_batch_eos_mid_accepted_run(monkeypatch):
  """EOS produced INSIDE an accepted run: the host cuts the emit at the EOS
  token exactly like a plain chunk, the extra accepted tokens are dropped,
  and the pool fully recovers."""
  monkeypatch.setenv("XOT_TPU_PAGED", "1")
  monkeypatch.setenv("XOT_TPU_PAGE_SIZE", "16")
  engine, params, shard = _echo_engine()
  solo = _single_row_reference(params, shard, [3, 25, 9], 12, cfg=CFG)
  eos = solo[3]  # lands mid-chunk, inside the echo draft's accepted run
  ref = solo[: solo.index(eos) + 1]

  server = BatchedServer(engine, n_slots=2, chunk=4, lookahead=True, spec_batch=True)
  outs, _ = _serve(server, [[3, 25, 9]], 40, eos_ids=(eos,))
  assert outs[0] == ref and outs[0][-1] == eos
  assert all(s is None for s in server.slots)
  alloc = server.allocator
  assert alloc.n_available == alloc.n_pages - 1  # every page recovered
  server.shutdown()


def test_spec_batch_gamma_collapses_and_falls_back_to_plain(monkeypatch):
  """Adversarial (acceptance≈0) drafts drive every row's gamma to 0 through
  the EWMA policy; once all rows sit at the floor the scheduler dispatches
  the PLAIN program (the batch is no longer dragged through draft+verify
  rounds), and the stream stays identical throughout the transition."""
  monkeypatch.setenv("XOT_TPU_PAGED", "1")
  monkeypatch.setenv("XOT_TPU_PAGE_SIZE", "16")
  monkeypatch.setenv("XOT_TPU_SPEC_REPROBE", "1000")  # no re-probe inside this test
  engine, params, shard = _random_engine(cfg=tiny_test_config(n_layers=2, max_seq_len=512, tied_embedding=True))
  cfg = engine.cfg
  # Make the draft truly adversarial (unrelated weights, ~zero agreement).
  engine._draft_params = quantize_params(full_model_params(jax.random.PRNGKey(777), cfg, "m")[0])

  server = BatchedServer(engine, n_slots=2, chunk=4, lookahead=True, spec_batch=True)
  spec_gammas = []
  orig = server.ops.spec_paged_batch_decode

  def spy(token, pool, cache_d, bt, pos, active, gammas, *a, **k):
    spec_gammas.append(np.asarray(gammas).copy())
    return orig(token, pool, cache_d, bt, pos, active, gammas, *a, **k)

  server.ops.spec_paged_batch_decode = spy
  prompt = [3, 25, 9]
  expected = _single_row_reference(params, shard, prompt, 79, cfg=cfg)
  outs, _ = _serve(server, [prompt], 80)
  assert outs[0] == expected
  assert spec_gammas, "speculative chunks never dispatched"
  # Depth walked down to the floor...
  assert spec_gammas[0].max() == server.spec_gamma_max
  assert spec_gammas[-1].max() <= 1
  peaks = [int(g.max()) for g in spec_gammas]
  assert all(a >= b for a, b in zip(peaks, peaks[1:])), f"gamma not monotone under 0 acceptance: {peaks}"
  # ...and the scheduler then STOPPED dispatching spec chunks: the stream is
  # 80 tokens ≈ 20 chunks, the spec spy saw only the pre-collapse prefix.
  assert len(spec_gammas) <= 8, f"batch kept paying for a dead draft: {len(spec_gammas)} spec chunks"
  server.shutdown()


def test_spec_batch_env_off_is_plain_byte_for_byte(monkeypatch):
  """XOT_TPU_SPEC_BATCH=0: the spec programs are never invoked, no draft
  cache is built, pool sizing is untouched, and output equals the plain
  server's."""
  monkeypatch.setenv("XOT_TPU_PAGED", "1")
  monkeypatch.setenv("XOT_TPU_PAGE_SIZE", "16")
  monkeypatch.setenv("XOT_TPU_SPEC_BATCH", "0")
  engine, params, shard = _echo_engine()
  server = BatchedServer(engine, n_slots=2, chunk=4, lookahead=True)
  called = []
  server.ops.spec_paged_batch_decode = lambda *a, **k: called.append(1)
  server.ops.spec_batch_decode = lambda *a, **k: called.append(1)
  expected = [_single_row_reference(params, shard, p, 7, cfg=CFG) for p in PROMPTS[:2]]
  outs, _ = _serve(server, PROMPTS[:2], 8)
  assert outs == expected
  assert not server.spec and server.draft_cache is None and not called
  server.shutdown()

  # And auto mode without a draft resolves OFF too (plain engines unchanged).
  plain_params, plain_shard = full_model_params(KEY, CFG, "m")
  plain_engine = JaxShardedInferenceEngine(use_local_mesh=False)
  plain_engine.load_test_model(plain_shard, CFG, plain_params)
  monkeypatch.delenv("XOT_TPU_SPEC_BATCH", raising=False)
  server2 = BatchedServer(plain_engine, n_slots=2, chunk=4)
  server2._ensure_cache()
  assert not server2.spec and server2.draft_cache is None
  server2.shutdown()


def test_spec_batch_sampled_rows_run_gamma_zero_same_stream(monkeypatch):
  """Sampled (temp>0) rows always run gamma 0 inside spec chunks and draw
  ONE sample per round — the same split-per-step schedule as the plain
  program — so a seeded sampled stream is identical with speculation on or
  off, even while a greedy row in the same batch speculates. (This is the
  documented sampled-stream contract; resume of sampled streams keeps the
  key-schedule caveat the QoS preempt-resume docs pin.)"""
  monkeypatch.setenv("XOT_TPU_PAGED", "1")
  monkeypatch.setenv("XOT_TPU_PAGE_SIZE", "16")
  engine, params, shard = _echo_engine()
  outs = {}
  for spec in (True, False):
    engine._key = jax.random.PRNGKey(123)  # identical key schedules
    server = BatchedServer(engine, n_slots=2, chunk=4, lookahead=True, spec_batch=spec)
    streams: dict[str, list] = {}

    async def run(server=server, streams=streams):
      def emit(rid, toks, finished):
        streams.setdefault(rid, []).extend(toks)

      return await asyncio.gather(
        server.submit("greedy", np.asarray([3, 25, 9], np.int32), max_tokens=8, temp=0.0, top_k=35, eos_ids=(), emit=emit),
        server.submit("sampled", np.asarray([7, 1, 88], np.int32), max_tokens=8, temp=0.8, top_k=35, eos_ids=(), emit=emit),
      )

    outs[spec] = asyncio.run(run())
    server.shutdown()
  assert outs[True] == outs[False], f"sampled/greedy mix diverged: {outs[True]} != {outs[False]}"
  assert len(outs[True][1]) == 8


def test_spec_batch_preempt_resume_mid_speculation(monkeypatch):
  """QoS preemption of a row that is mid-speculation: the boundary drains
  the pipeline, the victim resumes token-identically (its prompt absorbs
  the generated tokens), and the preemptor's stream is exact — speculation
  state (gamma, EWMA) restarts fresh at re-admission."""
  from xotorch_support_jetson_tpu.inference.qos import QosConfig, QosPolicy

  monkeypatch.setenv("XOT_TPU_PAGED", "1")
  monkeypatch.setenv("XOT_TPU_PAGE_SIZE", "16")
  monkeypatch.setenv("XOT_TPU_KV_TIER", "0")  # preempt via carry_tokens recompute
  engine, params, shard = _echo_engine()
  qos = QosPolicy(QosConfig(preempt=True, aging_s=1e9))
  server = BatchedServer(engine, n_slots=1, chunk=4, lookahead=True, qos=qos, spec_batch=True)
  solo_long = _single_row_reference(params, shard, [3, 25, 9], 30, cfg=CFG)
  solo_hi = _single_row_reference(params, shard, [7, 1, 88, 42, 5], 7, cfg=CFG)

  async def run():
    started = asyncio.Event()

    def emit(rid, toks, fin):
      if rid == "long" and toks:
        started.set()

    long_task = asyncio.create_task(
      server.submit("long", np.asarray([3, 25, 9], np.int32), max_tokens=31, temp=0.0, top_k=35, eos_ids=(), emit=emit, priority="batch")
    )
    await asyncio.wait_for(started.wait(), timeout=30)
    hi = await asyncio.wait_for(
      server.submit("hi", np.asarray([7, 1, 88, 42, 5], np.int32), max_tokens=8, temp=0.0, top_k=35, eos_ids=(), emit=emit, priority="interactive"),
      timeout=60,
    )
    return hi, await asyncio.wait_for(long_task, timeout=60)

  before = gm.counter_value("qos_preemptions_total")
  hi, long_out = asyncio.run(run())
  assert gm.counter_value("qos_preemptions_total") > before, "no preemption happened"
  assert hi == solo_hi
  assert long_out == solo_long
  server.shutdown()


def test_spec_batch_draft_kv_accounting(monkeypatch):
  """ISSUE 7 satellite: enabling speculation shrinks the DEFAULT page pool
  by the draft cache's byte footprint (expressed in page equivalents) so
  admission can't oversubscribe the same HBM budget, and the kv_draft_*
  gauges expose it. An explicit XOT_TPU_BATCH_PAGES stays untouched."""
  monkeypatch.setenv("XOT_TPU_PAGED", "1")
  monkeypatch.setenv("XOT_TPU_PAGE_SIZE", "16")
  engine, params, shard = _echo_engine()

  server_off = BatchedServer(engine, n_slots=2, chunk=4, spec_batch=False)
  server_off._ensure_cache()
  pages_off = server_off.allocator.n_pages
  server_off.shutdown()

  server_on = BatchedServer(engine, n_slots=2, chunk=4, spec_batch=True)
  server_on._ensure_cache()
  pages_on = server_on.allocator.n_pages
  assert server_on.spec and server_on.draft_cache is not None
  assert pages_on < pages_off, f"draft KV never entered pool sizing ({pages_on} vs {pages_off})"
  assert gm.gauges.get("kv_draft_bytes", 0) > 0
  assert gm.gauges.get("kv_draft_slots") == 2
  equiv = gm.gauges.get("kv_draft_pages_equivalent", 0)
  assert pages_off - pages_on == min(equiv, pages_off - server_on.pages_per_row - 2) or pages_on >= server_on.pages_per_row + 2
  server_on.shutdown()

  monkeypatch.setenv("XOT_TPU_BATCH_PAGES", "40")
  server_pin = BatchedServer(engine, n_slots=2, chunk=4, spec_batch=True)
  server_pin._ensure_cache()
  assert server_pin.allocator.n_pages == 40  # operator pin wins
  server_pin.shutdown()


def test_spec_policy_table():
  """The per-row depth policy (inference/paging.py): promote above 0.55,
  hold through the hysteresis band, demote below 0.30 (interactive: 0.15),
  floor at 0 — and the worst-advance/headroom math the scheduler plans by."""
  from xotorch_support_jetson_tpu.inference.paging import ewma_update, spec_adapt_gamma, spec_worst_advance

  assert spec_adapt_gamma(0.9, 2, 4) == 3  # promote
  assert spec_adapt_gamma(0.9, 4, 4) == 4  # promote caps at gamma_max
  assert spec_adapt_gamma(0.4, 3, 4) == 3  # hold (hysteresis band)
  assert spec_adapt_gamma(0.2, 4, 4) == 2  # demote halves
  assert spec_adapt_gamma(0.01, 1, 4) == 0  # floor: plain decode
  assert spec_adapt_gamma(0.01, 0, 4) == 0  # stays on the floor (probe is the caller's)
  assert spec_adapt_gamma(None, 3, 4) == 3  # no measurement yet: hold
  # Interactive rows demote later: accepted runs cut their ITL directly.
  assert spec_adapt_gamma(0.2, 4, 4, priority="interactive") == 4
  assert spec_adapt_gamma(0.1, 4, 4, priority="interactive") == 2

  assert spec_worst_advance(8, 4) == 40
  assert spec_worst_advance(4, 1) == 8

  assert ewma_update(None, 0.5) == 0.5
  assert abs(ewma_update(0.5, 1.0, alpha=0.3) - 0.65) < 1e-9
  assert ewma_update(0.5, 2.0) <= 1.0  # observations clamp to [0, 1]


def test_spec_kv_cache_bytes_block_math():
  """Draft-accounting block math: bf16 vs int8 per-token bytes match the
  layout init_kv_cache/init_paged_pool actually allocate."""
  import jax.numpy as jnp

  from xotorch_support_jetson_tpu.inference.paging import kv_cache_bytes
  from xotorch_support_jetson_tpu.models.decoder import init_kv_cache

  cfg = tiny_test_config(n_layers=2, max_seq_len=64)
  for quant in ("", "int8"):
    cache = init_kv_cache(cfg, 2, 1, 64, quant=quant)
    real = sum(int(np.prod(v.shape)) * jnp.dtype(v.dtype).itemsize for v in cache.values())
    assert kv_cache_bytes(cfg, 2, 64, quant) == real, quant


def test_spec_batch_interactive_rows_start_deeper(monkeypatch):
  """QoS interaction: interactive/standard rows open at full depth, batch
  rows start shallow (they must earn depth through acceptance)."""
  from xotorch_support_jetson_tpu.inference.qos import QosConfig, QosPolicy

  monkeypatch.setenv("XOT_TPU_PAGED", "1")
  monkeypatch.setenv("XOT_TPU_PAGE_SIZE", "16")
  engine, params, shard = _echo_engine()
  server = BatchedServer(engine, n_slots=4, chunk=4, lookahead=False, qos=QosPolicy(QosConfig()), spec_batch=True)
  seen = {}
  orig = server.ops.spec_paged_batch_decode

  def spy(token, pool, cache_d, bt, pos, active, gammas, *a, **k):
    g = np.asarray(gammas)
    for i in range(g.shape[0]):
      if g[i] > 0 and i not in seen:
        seen[i] = int(g[i])
    return orig(token, pool, cache_d, bt, pos, active, gammas, *a, **k)

  server.ops.spec_paged_batch_decode = spy

  async def run():
    emit = lambda *_: None
    await asyncio.gather(
      server.submit("i", np.asarray([3, 25, 9], np.int32), max_tokens=6, temp=0.0, top_k=35, eos_ids=(), emit=emit, priority="interactive"),
      server.submit("b", np.asarray([7, 1, 88], np.int32), max_tokens=6, temp=0.0, top_k=35, eos_ids=(), emit=emit, priority="batch"),
    )

  asyncio.run(run())
  server.shutdown()
  rows = sorted(seen.values(), reverse=True)
  assert rows and rows[0] == server.spec_gamma_max  # interactive at full depth
  assert min(seen.values()) == max(server.spec_gamma_max // 2, 1)  # batch shallow
