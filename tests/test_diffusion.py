"""Stable-diffusion stack tests.

Parity strategy (the reference's SD path is dead code — registry entry
commented out at ``reference models.py:167-168`` — so there is no reference
behavior to mirror beyond the API surface):

- CLIP text encoder: golden vs ``transformers.CLIPTextModel`` through the
  diffusers-format loader (the same strategy as tests/test_hf_golden.py).
- Samplers: analytic — for a delta data distribution the exact eps-model is
  known in closed form, and DDIM must recover x0 exactly; v-prediction and
  Euler must agree with it.
- UNet/VAE: structural + behavioral (diffusers is not installable here):
  loader→init tree equality, cross-attention sensitivity, skip wiring,
  shape contracts, img2img determinism.
"""

import os
import tempfile
from pathlib import Path

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from xotorch_support_jetson_tpu.models.diffusion import (
  ClipTextConfig,
  add_noise,
  alphas_cumprod,
  clip_text_encode,
  ddim_step,
  ddim_timesteps,
  euler_step,
  sample_chunk,
  tiny_diffusion_config,
  unet_apply,
  vae_decode,
  vae_encode,
  vae_sample_latents,
)
from xotorch_support_jetson_tpu.models.diffusion_loader import (
  init_clip_text_params,
  init_diffusion_params,
  init_unet_params,
  init_vae_params,
)
from xotorch_support_jetson_tpu.inference.diffusion_pipeline import DiffusionPipeline


CFG = tiny_diffusion_config()


@pytest.fixture(scope="module")
def params():
  return init_diffusion_params(jax.random.PRNGKey(0), CFG)


# ------------------------------------------------------------- CLIP golden


def test_clip_text_golden_vs_transformers():
  torch = pytest.importorskip("torch")
  from safetensors.torch import save_file
  from transformers import CLIPTextConfig as HFCfg, CLIPTextModel
  from xotorch_support_jetson_tpu.models.diffusion_loader import load_clip_text

  hf = HFCfg(
    vocab_size=99, hidden_size=32, intermediate_size=64, num_hidden_layers=3,
    num_attention_heads=4, max_position_embeddings=16, hidden_act="gelu",
  )
  torch.manual_seed(0)
  model = CLIPTextModel(hf).eval()
  tokens = torch.randint(0, 99, (2, 16))
  with torch.no_grad():
    ref = model(tokens).last_hidden_state.numpy()

  jcfg = ClipTextConfig(
    vocab_size=99, hidden_size=32, intermediate_size=64, n_layers=3, n_heads=4,
    max_positions=16, act="gelu",
  )
  with tempfile.TemporaryDirectory() as d:
    save_file({k: v.contiguous() for k, v in model.state_dict().items()}, os.path.join(d, "model.safetensors"))
    loaded = load_clip_text(Path(d), jcfg)
  out = np.asarray(clip_text_encode(loaded, jcfg, jnp.asarray(tokens.numpy())))
  np.testing.assert_allclose(out, ref, atol=2e-5)


def test_clip_quick_gelu_differs():
  """SD1 checkpoints use quick_gelu; the act flag must change the output."""
  cfg_g = ClipTextConfig(vocab_size=64, hidden_size=16, intermediate_size=32, n_layers=1, n_heads=2, max_positions=8, act="gelu")
  cfg_q = ClipTextConfig(**{**cfg_g.__dict__, "act": "quick_gelu"})
  p = init_clip_text_params(jax.random.PRNGKey(1), cfg_g)
  toks = jnp.asarray([[0, 5, 9, 3, 1, 1, 1, 1]])
  a = clip_text_encode(p, cfg_g, toks)
  b = clip_text_encode(p, cfg_q, toks)
  assert not np.allclose(np.asarray(a), np.asarray(b))


# --------------------------------------------------------- sampler analytic


def _delta_eps_model(x0, alphas):
  """Exact eps-predictor for a delta data distribution at x0."""

  def fn(_params, x, t, _ctx):
    a_t = alphas[t][:, None, None, None]
    return (x - jnp.sqrt(a_t) * x0) / jnp.sqrt(1.0 - a_t)

  return fn


def test_ddim_recovers_delta_x0_exactly():
  """With the exact eps model, every DDIM step lands on the exact posterior
  mean; after the final step (a_prev = 1) the sample IS x0."""
  alphas = jnp.asarray(alphas_cumprod(CFG))
  x0 = jax.random.normal(jax.random.PRNGKey(2), (1, 4, 4, 4))
  ts = np.asarray(ddim_timesteps(CFG, 10), np.int32)
  a_ts = np.asarray(alphas)[ts]
  prev = ts - CFG.num_train_timesteps // 10
  a_prevs = np.where(prev >= 0, np.asarray(alphas)[np.clip(prev, 0, None)], 1.0).astype(np.float32)

  x2 = jnp.concatenate([x0, x0], axis=0)
  latents = jax.random.normal(jax.random.PRNGKey(3), x0.shape)
  out = sample_chunk(
    {}, CFG, latents, jnp.zeros((2, 1, 1)),
    jnp.asarray(ts), jnp.asarray(a_ts), jnp.asarray(a_prevs),
    guidance=1.0, unet_fn=_delta_eps_model(x2, alphas),
  )
  np.testing.assert_allclose(np.asarray(out), np.asarray(x0), atol=1e-4)


def test_v_prediction_equals_epsilon_step():
  """The same (x, x0) expressed in both parameterizations must produce the
  same DDIM and Euler updates."""
  rng = jax.random.PRNGKey(4)
  x0 = jax.random.normal(rng, (2, 3, 3, 4))
  eps = jax.random.normal(jax.random.fold_in(rng, 1), x0.shape)
  a_t, a_prev = 0.5, 0.8
  x = add_noise(x0, eps, a_t)
  v = np.sqrt(a_t) * eps - np.sqrt(1 - a_t) * x0
  for step in (ddim_step, euler_step):
    out_eps = step(x, eps, a_t, a_prev, "epsilon")
    out_v = step(x, v, a_t, a_prev, "v_prediction")
    np.testing.assert_allclose(np.asarray(out_eps), np.asarray(out_v), atol=1e-5)


def test_euler_recovers_delta_x0():
  """Euler in sigma space also converges on the delta distribution (exact
  probability-flow line: d is constant, so one step per interval is exact)."""
  alphas = jnp.asarray(alphas_cumprod(CFG))
  x0 = jax.random.normal(jax.random.PRNGKey(5), (1, 4, 4, 4))
  ts = np.asarray(ddim_timesteps(CFG, 8), np.int32)
  a_ts = np.asarray(alphas)[ts]
  prev = ts - CFG.num_train_timesteps // 8
  a_prevs = np.where(prev >= 0, np.asarray(alphas)[np.clip(prev, 0, None)], 1.0 - 1e-7).astype(np.float32)
  latents = jax.random.normal(jax.random.PRNGKey(6), x0.shape)
  out = sample_chunk(
    {}, CFG, latents, jnp.zeros((2, 1, 1)),
    jnp.asarray(ts), jnp.asarray(a_ts), jnp.asarray(a_prevs),
    guidance=1.0, method="euler", unet_fn=_delta_eps_model(jnp.concatenate([x0, x0]), alphas),
  )
  np.testing.assert_allclose(np.asarray(out), np.asarray(x0), atol=1e-3)


def test_cfg_guidance_one_is_cond_only():
  """guidance=1 ⇒ uncond contribution cancels: out = out_cond."""
  alphas = jnp.asarray(alphas_cumprod(CFG))
  ts = np.asarray([500], np.int32)
  a = np.asarray(alphas)[ts]

  x0_cond = jnp.ones((1, 2, 2, 4))
  x0_uncond = -jnp.ones((1, 2, 2, 4))
  pair = jnp.concatenate([x0_uncond, x0_cond], axis=0)
  latents = jax.random.normal(jax.random.PRNGKey(7), (1, 2, 2, 4))
  out_g1 = sample_chunk({}, CFG, latents, jnp.zeros((2, 1, 1)), jnp.asarray(ts), jnp.asarray(a), jnp.asarray([1.0]), guidance=1.0, unet_fn=_delta_eps_model(pair, alphas))
  out_cond_only = sample_chunk({}, CFG, latents, jnp.zeros((2, 1, 1)), jnp.asarray(ts), jnp.asarray(a), jnp.asarray([1.0]), guidance=1.0, unet_fn=_delta_eps_model(jnp.concatenate([x0_cond, x0_cond]), alphas))
  np.testing.assert_allclose(np.asarray(out_g1), np.asarray(out_cond_only), atol=1e-5)


# ------------------------------------------------------------ UNet behavior


def test_unet_shapes_and_determinism(params):
  x = jax.random.normal(jax.random.PRNGKey(8), (2, 8, 8, 4))
  t = jnp.asarray([10, 500])
  ctx = jax.random.normal(jax.random.PRNGKey(9), (2, 7, CFG.unet.cross_attention_dim))
  out = unet_apply(params["unet"], CFG.unet, x, t, ctx)
  assert out.shape == (2, 8, 8, 4)
  assert np.isfinite(np.asarray(out)).all()
  out2 = unet_apply(params["unet"], CFG.unet, x, t, ctx)
  np.testing.assert_array_equal(np.asarray(out), np.asarray(out2))


def test_unet_cross_attention_sees_text(params):
  x = jax.random.normal(jax.random.PRNGKey(10), (1, 8, 8, 4))
  t = jnp.asarray([100])
  ctx_a = jax.random.normal(jax.random.PRNGKey(11), (1, 7, CFG.unet.cross_attention_dim))
  ctx_b = ctx_a + 1.0
  a = unet_apply(params["unet"], CFG.unet, x, t, ctx_a)
  b = unet_apply(params["unet"], CFG.unet, x, t, ctx_b)
  assert not np.allclose(np.asarray(a), np.asarray(b))


def test_unet_timestep_matters(params):
  x = jax.random.normal(jax.random.PRNGKey(12), (1, 8, 8, 4))
  ctx = jax.random.normal(jax.random.PRNGKey(13), (1, 7, CFG.unet.cross_attention_dim))
  a = unet_apply(params["unet"], CFG.unet, x, jnp.asarray([1]), ctx)
  b = unet_apply(params["unet"], CFG.unet, x, jnp.asarray([999]), ctx)
  assert not np.allclose(np.asarray(a), np.asarray(b))


# ------------------------------------------------------------ VAE behavior


def test_vae_roundtrip_shapes(params):
  img = jax.random.uniform(jax.random.PRNGKey(14), (1, 16, 16, 3), minval=-1, maxval=1)
  moments = vae_encode(params["vae"], CFG.vae, img)
  # 2 levels ⇒ one stride-2 downsample: 16 → 8 spatial, 2*latent channels
  assert moments.shape == (1, 8, 8, 2 * CFG.vae.latent_channels)
  z = vae_sample_latents(moments, jax.random.PRNGKey(15), CFG.vae.scaling_factor)
  out = vae_decode(params["vae"], CFG.vae, z)
  assert out.shape == (1, 16, 16, 3)
  assert np.isfinite(np.asarray(out)).all()


def test_vae_sample_latents_deterministic_at_zero_var():
  moments = jnp.concatenate([jnp.full((1, 2, 2, 4), 3.0), jnp.full((1, 2, 2, 4), -40.0)], axis=-1)
  z = vae_sample_latents(moments, jax.random.PRNGKey(16), 0.5)
  np.testing.assert_allclose(np.asarray(z), 1.5, atol=1e-4)  # mean*scaling, var≈0 (logvar clipped at -30)


# ----------------------------------------------------------- loader parity


def test_loader_tree_matches_init_tree():
  """A diffusers-named checkpoint written by the SHIPPING exporter
  (export_diffusers_checkpoint — the same name map the verify drill uses)
  must load back into the identical tree, values, and behavior: one name
  map, round-tripped in both directions."""
  from xotorch_support_jetson_tpu.models.diffusion_loader import (
    export_diffusers_checkpoint,
    load_unet,
    load_vae,
  )

  rng = jax.random.PRNGKey(17)
  params = init_diffusion_params(rng, CFG)

  with tempfile.TemporaryDirectory() as d:
    export_diffusers_checkpoint(Path(d), CFG, params)
    unet_l = load_unet(Path(d) / "unet", CFG.unet)
    vae_l = load_vae(Path(d) / "vae", CFG.vae)

  for orig, loaded, name in ((params["unet"], unet_l, "unet"), (params["vae"], vae_l, "vae")):
    flat_o = jax.tree_util.tree_flatten_with_path(orig)[0]
    flat_l = jax.tree_util.tree_flatten_with_path(loaded)[0]
    assert len(flat_o) == len(flat_l), name
    for (po, lo), (pl, ll) in zip(flat_o, flat_l):
      assert jax.tree_util.keystr(po) == jax.tree_util.keystr(pl), name
      np.testing.assert_allclose(np.asarray(lo), np.asarray(ll), atol=1e-6, err_msg=f"{name}{jax.tree_util.keystr(po)}")

  # the loaded tree must also RUN identically
  x = jax.random.normal(jax.random.PRNGKey(18), (1, 8, 8, 4))
  ctx = jax.random.normal(jax.random.PRNGKey(19), (1, 5, CFG.unet.cross_attention_dim))
  a = unet_apply(params["unet"], CFG.unet, x, jnp.asarray([3]), ctx)
  b = unet_apply(unet_l, CFG.unet, x, jnp.asarray([3]), ctx)
  np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-6)


def test_exported_checkpoint_loads_as_full_pipeline():
  """export → diffusion_config_from_dir → load_diffusion_params: the whole
  offline-checkpoint path the verify drill and the engine take."""
  from xotorch_support_jetson_tpu.models.diffusion_loader import (
    diffusion_config_from_dir,
    export_diffusers_checkpoint,
    load_diffusion_params,
  )

  params = init_diffusion_params(jax.random.PRNGKey(21), CFG)
  with tempfile.TemporaryDirectory() as d:
    export_diffusers_checkpoint(Path(d), CFG, params)
    cfg2 = diffusion_config_from_dir(Path(d))
    # the exporter writes explicit per-level head counts; the reloaded config
    # must be FUNCTIONALLY identical (same heads at every level)
    from dataclasses import replace as _dc_replace

    n_lv = len(CFG.unet.block_out_channels)
    assert [cfg2.unet.heads_at(i) for i in range(n_lv)] == [CFG.unet.heads_at(i) for i in range(n_lv)]
    assert _dc_replace(cfg2.unet, attn_heads=None, attention_head_dim=CFG.unet.attention_head_dim) == CFG.unet
    assert cfg2.vae == CFG.vae and cfg2.clip == CFG.clip
    assert cfg2.set_alpha_to_one == CFG.set_alpha_to_one and cfg2.steps_offset == CFG.steps_offset
    loaded = load_diffusion_params(Path(d), cfg2)
  pipe_a = DiffusionPipeline(CFG, params, dtype=jnp.float32)
  pipe_b = DiffusionPipeline(cfg2, loaded, dtype=jnp.float32)
  img_a = pipe_a.generate("same words", steps=4, seed=9)
  img_b = pipe_b.generate("same words", steps=4, seed=9)
  np.testing.assert_array_equal(img_a, img_b)


# -------------------------------------------------------------- pipeline


def test_pipeline_generate_and_img2img(params):
  pipe = DiffusionPipeline(CFG, params, dtype=jnp.float32, progress_chunk=3)
  prog = []
  img = pipe.generate("a red cube", steps=7, guidance=4.0, seed=1, progress_cb=lambda d, t: prog.append((d, t)))
  assert img.shape == (16, 16, 3) and img.dtype == np.uint8
  assert prog[0] == (0, 7) and prog[-1] == (7, 7)
  assert [d for d, _ in prog] == sorted(d for d, _ in prog)

  # deterministic per seed; prompt-sensitive
  img_b = pipe.generate("a red cube", steps=7, guidance=4.0, seed=1)
  np.testing.assert_array_equal(img, img_b)
  img_c = pipe.generate("a blue sphere", steps=7, guidance=4.0, seed=1)
  assert not np.array_equal(img, img_c)

  # img2img consumes the init image and differs from text-to-image
  i2i = pipe.generate("a red cube", steps=7, seed=2, init_image=img, strength=0.5)
  assert i2i.shape == (16, 16, 3)
  assert not np.array_equal(i2i, img)


def test_pipeline_euler_method(params):
  pipe = DiffusionPipeline(CFG, params, dtype=jnp.float32)
  img = pipe.generate("cube", steps=5, method="euler", seed=3)
  assert img.shape == (16, 16, 3)


def test_pipeline_snaps_offgrid_sizes(params):
  """Off-grid sizes must round to the model's pixel grid (px_multiple =
  vae_stride x unet_stride), never shape-mismatch the UNet skip concats."""
  pipe = DiffusionPipeline(CFG, params, dtype=jnp.float32)
  assert pipe.px_multiple == 4  # 2-level VAE x 2-level UNet
  img = pipe.generate("cube", steps=3, seed=1, size=(18, 18))
  assert img.shape == (20, 20, 3)
  # off-grid init image resizes internally instead of crashing
  init = np.zeros((18, 18, 3), np.uint8)
  i2i = pipe.generate("cube", steps=4, seed=1, init_image=init, strength=0.5)
  assert i2i.shape == (20, 20, 3)


def test_pipeline_cancellation(params):
  """should_cancel is polled between chunks; firing it aborts the denoise
  (the API sets it on client disconnect — the single engine worker must not
  finish a dead request)."""
  from xotorch_support_jetson_tpu.inference.diffusion_pipeline import GenerationCancelled

  pipe = DiffusionPipeline(CFG, params, dtype=jnp.float32, progress_chunk=2)
  seen = []

  def cancel_after_first_chunk():
    return len(seen) >= 2  # progress fires at 0 then after each chunk

  with pytest.raises(GenerationCancelled):
    pipe.generate("cube", steps=8, seed=1, progress_cb=lambda d, t: seen.append(d), should_cancel=cancel_after_first_chunk)
  assert seen[-1] < 8  # never ran to completion


def test_steps_offset_shifts_timesteps():
  """SD scheduler configs ship steps_offset=1 (diffusers leading spacing);
  the lowest timestep becomes offset, not 0."""
  from dataclasses import replace

  cfg1 = replace(CFG, steps_offset=1)
  ts0 = np.asarray(ddim_timesteps(CFG, 10))
  ts1 = np.asarray(ddim_timesteps(cfg1, 10))
  assert ts0[-1] == 0 and ts1[-1] == 1
  np.testing.assert_array_equal(ts1, np.clip(ts0 + 1, 0, CFG.num_train_timesteps - 1))


def test_sd_download_patterns_skip_monolithic_checkpoints():
  """The diffusers repo layout must fetch only per-component weights — not
  the multi-GB root checkpoints or .fp16 duplicates."""
  from xotorch_support_jetson_tpu.download.hf_utils import filter_repo_objects, get_allow_patterns
  from xotorch_support_jetson_tpu.inference.shard import Shard

  shard = Shard("stable-diffusion-2-1-base", 0, 30, 31)
  patterns = get_allow_patterns(None, shard)
  repo_files = [
    "model_index.json", "v2-1_512-ema-pruned.safetensors", "v2-1_512-nonema-pruned.safetensors",
    "text_encoder/config.json", "text_encoder/model.safetensors", "text_encoder/model.fp16.safetensors",
    "unet/config.json", "unet/diffusion_pytorch_model.safetensors", "unet/diffusion_pytorch_model.fp16.safetensors",
    "vae/config.json", "vae/diffusion_pytorch_model.safetensors", "vae/diffusion_pytorch_model.fp16.safetensors",
    "scheduler/scheduler_config.json", "tokenizer/vocab.json", "tokenizer/merges.txt",
  ]
  got = set(filter_repo_objects(repo_files, allow_patterns=patterns))
  assert "unet/diffusion_pytorch_model.safetensors" in got
  assert "text_encoder/model.safetensors" in got and "vae/diffusion_pytorch_model.safetensors" in got
  assert "scheduler/scheduler_config.json" in got and "tokenizer/merges.txt" in got
  assert not any("fp16" in f or f.startswith("v2-1_512") for f in got), got
  # text models keep the bare-safetensors fallback
  llama = get_allow_patterns(None, Shard("llama-3.2-1b", 0, 15, 16))
  assert "*.safetensors" in llama


def test_pipeline_n_candidates(params):
  """n>1 denoises as one batch; candidates differ (per-candidate noise) and
  n=1 output equals the first... of nothing — n=1 keeps the 3-D contract."""
  pipe = DiffusionPipeline(CFG, params, dtype=jnp.float32)
  batch = pipe.generate("cubes", steps=4, seed=11, n=3)
  assert batch.shape == (3, 16, 16, 3) and batch.dtype == np.uint8
  assert not np.array_equal(batch[0], batch[1])
  single = pipe.generate("cubes", steps=4, seed=11)
  assert single.shape == (16, 16, 3)


def test_sd1_style_geometry_runs():
  """SD1-family layout: per-level head COUNTS (attn_heads), quick_gelu CLIP,
  v-prediction scheduler — the variant axes a real 1.5 checkpoint exercises."""
  from dataclasses import replace

  base = tiny_diffusion_config()
  cfg = replace(
    base,
    clip=ClipTextConfig(**{**base.clip.__dict__, "act": "quick_gelu"}),
    unet=replace(base.unet, attn_heads=(2, 2), attention_head_dim=999),  # head counts win
    prediction_type="v_prediction",
  )
  assert cfg.unet.heads_at(0) == 2 and cfg.unet.heads_at(1) == 2
  params = init_diffusion_params(jax.random.PRNGKey(31), cfg)
  pipe = DiffusionPipeline(cfg, params, dtype=jnp.float32)
  img = pipe.generate("a cube", steps=4, seed=2)
  assert img.shape == (16, 16, 3)
  assert np.isfinite(img.astype(np.float32)).all()
