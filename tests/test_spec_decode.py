"""Fused speculative decoding exactness: for ANY draft, output must be
token-identical to plain greedy fused_generate (models/decoder.py
fused_speculative_generate — every emitted token is the target's own greedy
choice by construction)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from xotorch_support_jetson_tpu.models.config import tiny_test_config
from xotorch_support_jetson_tpu.models.decoder import (
  full_model_params,
  fused_generate,
  fused_speculative_generate,
  init_kv_cache,
  shard_forward,
)


def _greedy_reference(cfg, params, shard, prompt, max_steps, eos_ids):
  B, S = prompt.shape
  cache = init_kv_cache(cfg, shard.n_shard_layers, B, cfg.max_seq_len)
  positions = jnp.broadcast_to(jnp.arange(S, dtype=jnp.int32), (B, S))
  logits, cache = shard_forward(params, cfg, shard, jnp.asarray(prompt), positions, cache)
  first = jnp.argmax(logits[:, S - 1, :], axis=-1).astype(jnp.int32)[:, None]
  buf, n, _ = fused_generate(params, cfg, shard, first, cache, jnp.full((B,), S, jnp.int32), max_steps, eos_ids=eos_ids)
  row = np.asarray(buf)[0]
  out = [int(first[0, 0])]
  for tok in row[:max_steps]:
    out.append(int(tok))
    if int(tok) in eos_ids:
      break
  return out


def _spec_tokens(cfg_t, params_t, shard_t, cfg_d, params_d, shard_d, prompt, max_steps, eos_ids, gamma):
  B, S = prompt.shape
  cache_t = init_kv_cache(cfg_t, shard_t.n_shard_layers, B, cfg_t.max_seq_len)
  cache_d = init_kv_cache(cfg_d, shard_d.n_shard_layers, B, cfg_d.max_seq_len)
  positions = jnp.broadcast_to(jnp.arange(S, dtype=jnp.int32), (B, S))
  logits, cache_t = shard_forward(params_t, cfg_t, shard_t, jnp.asarray(prompt), positions, cache_t)
  _, cache_d = shard_forward(params_d, cfg_d, shard_d, jnp.asarray(prompt), positions, cache_d)
  first = jnp.argmax(logits[:, S - 1, :], axis=-1).astype(jnp.int32)[:, None]
  buf, n, _rounds, _, _ = fused_speculative_generate(
    params_t, cfg_t, shard_t, params_d, cfg_d, shard_d, first, cache_t, cache_d,
    jnp.int32(S), max_steps, gamma=gamma, eos_ids=eos_ids,
  )
  row = np.asarray(buf)[: int(n)]
  out = [int(first[0, 0])]
  for tok in row:
    out.append(int(tok))
    if int(tok) in eos_ids:
      break
    if len(out) - 1 >= max_steps:
      break
  return out


@pytest.mark.parametrize("gamma", [1, 3, 4])
def test_spec_decode_same_draft_is_exact(gamma):
  """draft == target: full acceptance, identical output."""
  cfg = tiny_test_config(n_layers=4, max_seq_len=128)
  params, shard = full_model_params(jax.random.PRNGKey(7), cfg, "m")
  prompt = np.array([[5, 9, 2, 71]], dtype=np.int32)
  ref = _greedy_reference(cfg, params, shard, prompt, 24, eos_ids=(-1,))
  got = _spec_tokens(cfg, params, shard, cfg, params, shard, prompt, 24, (-1,), gamma)
  assert got[: len(ref)] == ref


@pytest.mark.parametrize("gamma", [2, 4])
def test_spec_decode_unrelated_draft_is_exact(gamma):
  """A completely different (random) draft must STILL yield the target's
  exact greedy output — the draft can only change speed, never tokens."""
  cfg_t = tiny_test_config(n_layers=4, max_seq_len=128)
  params_t, shard_t = full_model_params(jax.random.PRNGKey(7), cfg_t, "m")
  cfg_d = tiny_test_config(n_layers=2, dim=32, hidden_dim=64, n_heads=2, n_kv_heads=1, max_seq_len=128)
  params_d, shard_d = full_model_params(jax.random.PRNGKey(99), cfg_d, "d")
  prompt = np.array([[5, 9, 2, 71]], dtype=np.int32)
  ref = _greedy_reference(cfg_t, params_t, shard_t, prompt, 20, eos_ids=(-1,))
  got = _spec_tokens(cfg_t, params_t, shard_t, cfg_d, params_d, shard_d, prompt, 20, (-1,), gamma)
  assert got[: len(ref)] == ref


def test_peaked_echo_model_hits_high_acceptance_and_stays_exact():
  """The peaked-logit synthetic model (utils/synthetic.py): the int8
  self-draft reaches near-full acceptance — the speculative win is
  measurable OFFLINE (bench.py spec_peak_* fields record it) — while the
  output stays token-identical to plain greedy.

  The acceptance assertion is a BUILD-VARIANCE CAPABILITY PROBE (ISSUE 7),
  not a loosened constant: the echo margin rides on int8-rounding noise and
  the backend's reduction order, so the test first MEASURES this build's
  draft/target argmax agreement along the greedy trajectory
  (spec_agreement_bitmap), replays the speculative accept rule on that
  bitmap (simulate_spec_acceptance), and pins the fused program to its own
  build's expectation — a program regression can no longer hide inside a
  hand-widened threshold, while genuine build variance passes by
  construction. The probe itself keeps a floor: if THIS build's agreement
  collapses, the ceiling construction has regressed."""
  from xotorch_support_jetson_tpu.models.quantize import quantize_params
  from xotorch_support_jetson_tpu.utils.synthetic import peaked_echo_params, simulate_spec_acceptance, spec_agreement_bitmap

  cfg = tiny_test_config(n_layers=4, max_seq_len=128, tied_embedding=True)
  base, shard = full_model_params(jax.random.PRNGKey(7), cfg, "m")
  params = peaked_echo_params(base)
  qp = quantize_params(params)
  gamma, max_steps = 4, 24
  prompt = np.array([[5, 9, 2, 71]], dtype=np.int32)
  # Probe trajectory runs gamma past max_steps: the fused loop's final round
  # emits its full accepted run beyond the limit, and the replay needs those
  # agreement bits to predict n/rounds exactly.
  probe_traj = _greedy_reference(cfg, params, shard, prompt, max_steps + gamma + 1, eos_ids=(-1,))[1:]
  ref = _greedy_reference(cfg, params, shard, prompt, max_steps, eos_ids=(-1,))

  B, S = prompt.shape
  cache_t = init_kv_cache(cfg, shard.n_shard_layers, B, cfg.max_seq_len)
  cache_d = init_kv_cache(cfg, shard.n_shard_layers, B, cfg.max_seq_len)
  positions = jnp.broadcast_to(jnp.arange(S, dtype=jnp.int32), (B, S))
  logits, cache_t = shard_forward(params, cfg, shard, jnp.asarray(prompt), positions, cache_t)
  _, cache_d = shard_forward(qp, cfg, shard, jnp.asarray(prompt), positions, cache_d)
  first = jnp.argmax(logits[:, S - 1, :], axis=-1).astype(jnp.int32)[:, None]
  buf, n, rounds, _, _ = fused_speculative_generate(
    params, cfg, shard, qp, cfg, shard, first, cache_t, cache_d, jnp.int32(S), max_steps, gamma=gamma, eos_ids=(-1,)
  )
  got = [int(first[0, 0])] + [int(t) for t in np.asarray(buf)[: int(n)]][:max_steps]
  assert got[: len(ref)] == ref
  acceptance = (int(n) / max(int(rounds), 1) - 1) / gamma

  # The trajectory the fused loop verifies against starts at `first`; the
  # bitmap covers the draft's agreement on every step after it.
  bits = spec_agreement_bitmap(params, cfg, shard, qp, cfg, shard, prompt, probe_traj)
  predicted = simulate_spec_acceptance(bits, gamma, max_steps)
  # Exact replay up to window-forward vs step-forward argmax near-ties
  # (the one numerics caveat fused_speculative_generate documents): allow a
  # one-flip margin, nothing more.
  assert abs(acceptance - predicted) <= 1.5 / max_steps, (
    f"measured acceptance {acceptance:.3f} diverged from this build's probed expectation {predicted:.3f}"
  )
  # Construction floor: the ECHO ceiling itself must still be a ceiling on
  # this build (worst measured build variance to date: 0.83).
  assert predicted >= 0.5, f"echo construction regressed: probed agreement predicts only {predicted:.3f}"


@pytest.mark.asyncio
async def test_engine_spec_decode_matches_plain_oneshot():
  """XOT_TPU_SPEC_DECODE=int8 engine path (prefill + generate_oneshot) must
  produce the exact plain-greedy token stream."""
  from xotorch_support_jetson_tpu.inference.jax_engine import JaxShardedInferenceEngine

  cfg = tiny_test_config(n_layers=4, max_seq_len=128)
  params, shard = full_model_params(jax.random.PRNGKey(11), cfg, "m")
  prompt = np.array([[5, 9, 2, 71, 33]], dtype=np.int32)

  plain = JaxShardedInferenceEngine(use_local_mesh=False)
  plain.load_test_model(shard, cfg, params)
  logits, _ = await plain.infer_tensor("a", shard, prompt)
  first = int(np.argmax(logits, -1)[0])
  ref = await plain.generate_oneshot("a", shard, first, 20, eos_ids=(-1,), temp=0.0)

  spec = JaxShardedInferenceEngine(use_local_mesh=False, spec_decode="int8")
  spec.load_test_model(shard, cfg, params)
  assert spec._draft_params is not None
  logits2, _ = await spec.infer_tensor("a", shard, prompt)
  assert int(np.argmax(logits2, -1)[0]) == first
  got = await spec.generate_oneshot("a", shard, first, 20, eos_ids=(-1,), temp=0.0)
  assert got == ref


def test_spec_decode_eos_trim_matches_reference():
  """EOS produced mid-round ends generation at the same token as plain
  greedy (use an eos id that actually occurs in the reference output)."""
  cfg = tiny_test_config(n_layers=4, max_seq_len=128)
  params, shard = full_model_params(jax.random.PRNGKey(3), cfg, "m")
  prompt = np.array([[17, 4, 99]], dtype=np.int32)
  probe = _greedy_reference(cfg, params, shard, prompt, 16, eos_ids=(-1,))
  eos = probe[len(probe) // 2]  # a token we know greedy decoding emits
  ref = _greedy_reference(cfg, params, shard, prompt, 16, eos_ids=(eos,))
  cfg_d = tiny_test_config(n_layers=2, dim=32, hidden_dim=64, n_heads=2, n_kv_heads=1, max_seq_len=128)
  params_d, shard_d = full_model_params(jax.random.PRNGKey(42), cfg_d, "d")
  got = _spec_tokens(cfg, params, shard, cfg_d, params_d, shard_d, prompt, 16, (eos,), 3)
  assert got == ref


def test_spec_chunk_chain_is_exact():
  """Streaming speculative chunks (models/decoder.py fused_speculative_chunk)
  chained through the DEVICE-side seed/pos must reproduce plain greedy
  token-for-token across chunk boundaries, for any draft."""
  from xotorch_support_jetson_tpu.models.decoder import fused_speculative_chunk

  cfg = tiny_test_config(n_layers=4, max_seq_len=128)
  params, shard = full_model_params(jax.random.PRNGKey(11), cfg, "m")
  params_d = jax.tree.map(lambda x: x, full_model_params(jax.random.PRNGKey(77), cfg, "m")[0])  # unrelated draft
  prompt = np.array([[5, 9, 2, 71, 33]], dtype=np.int32)
  ref = _greedy_reference(cfg, params, shard, prompt, 24, eos_ids=(-1,))

  B, S = prompt.shape
  cache_t = init_kv_cache(cfg, shard.n_shard_layers, B, cfg.max_seq_len)
  cache_d = init_kv_cache(cfg, shard.n_shard_layers, B, cfg.max_seq_len)
  positions = jnp.broadcast_to(jnp.arange(S, dtype=jnp.int32), (B, S))
  logits, cache_t = shard_forward(params, cfg, shard, jnp.asarray(prompt), positions, cache_t)
  _, cache_d = shard_forward(params_d, cfg, shard, jnp.asarray(prompt), positions, cache_d)
  token = jnp.argmax(logits[:, S - 1, :], axis=-1).astype(jnp.int32)[:, None]
  got = [int(token[0, 0])]
  pos = jnp.int32(S)
  for _ in range(4):  # 4 chunks of 6 = ref's 24 steps
    packed, token, pos, cache_t, cache_d = fused_speculative_chunk(
      params, cfg, shard, params_d, token, cache_t, cache_d, pos, steps=8, gamma=3, n_limit=6
    )
    row = np.asarray(packed)
    m, rounds = int(row[0]), int(row[1])
    assert 1 <= m <= 6
    assert 1 <= rounds <= m  # each round emits at least one token
    got.extend(int(t) for t in row[2 : 2 + m])
  assert got == ref[: len(got)]
  assert len(got) >= 1 + 4 * 1


@pytest.mark.asyncio
async def test_engine_streaming_spec_chunks_match_plain():
  """The engine's pipelined chunk path under XOT_TPU_SPEC_DECODE=int8:
  dispatch N+1 before reading N (exactly like the node's loop), tokens must
  equal the plain engine's chunked stream."""
  from xotorch_support_jetson_tpu.inference.jax_engine import JaxShardedInferenceEngine

  cfg = tiny_test_config(n_layers=4, max_seq_len=128)
  params, shard = full_model_params(jax.random.PRNGKey(11), cfg, "m")
  prompt = np.array([[5, 9, 2, 71, 33]], dtype=np.int32)

  async def drive_to_exhaustion(engine, rid, chunk):
    """The node's pipelined loop (dispatch N+1 before reading N) until the
    engine refuses for lack of cache room."""
    logits, _ = await engine.infer_tensor(rid, shard, prompt)
    first = int(np.argmax(logits, -1)[0])
    out = [first]
    pending = await engine.dispatch_chunk(rid, shard, chunk, 0.0, 35, first_token=first)
    while pending is not None:
      nxt = await engine.dispatch_chunk(rid, shard, chunk, 0.0, 35)
      out.extend(await engine.read_chunk(pending))
      pending = nxt
    return out

  plain = JaxShardedInferenceEngine(use_local_mesh=False)
  plain.load_test_model(shard, cfg, params)
  ref = await drive_to_exhaustion(plain, "a", 8)

  spec = JaxShardedInferenceEngine(use_local_mesh=False, spec_decode="int8")
  spec.load_test_model(shard, cfg, params)
  # First dispatch must actually take the spec path.
  logits2, _ = await spec.infer_tensor("probe", shard, prompt)
  h = spec._dispatch_chunk_sync("probe", shard, 8, 0.0, 35, int(np.argmax(logits2, -1)[0]))
  assert isinstance(h, tuple) and h[0] == "spec"

  # FULL stream to cache exhaustion, including the near-cache-end handoff to
  # the plain path with an unread (possibly truncated) spec chunk in flight:
  # the whole stream must be token-identical to the plain engine's, and both
  # must fill the cache to the same final position.
  got = await drive_to_exhaustion(spec, "b", 8)
  assert got == ref
  assert spec.sessions["b"].curr_pos == plain.sessions["a"].curr_pos <= cfg.max_seq_len

  # Mixed chunk sizes (the node shrinks n_steps near the token budget):
  # larger unread buckets must be accounted at THEIR size, not the current one.
  spec2 = JaxShardedInferenceEngine(use_local_mesh=False, spec_decode="int8")
  spec2.load_test_model(shard, cfg, params)
  logits3, _ = await spec2.infer_tensor("c", shard, prompt)
  first3 = int(np.argmax(logits3, -1)[0])
  got2 = [first3]
  sizes = [16, 16, 4, 4, 2, 8, 16, 2]
  pending = await spec2.dispatch_chunk("c", shard, sizes[0], 0.0, 35, first_token=first3)
  i = 1
  while pending is not None:
    nxt = await spec2.dispatch_chunk("c", shard, sizes[i % len(sizes)], 0.0, 35)
    i += 1
    got2.extend(await spec2.read_chunk(pending))
    pending = nxt
  assert got2 == ref[: len(got2)]
  assert spec2.sessions["c"].curr_pos <= cfg.max_seq_len


@pytest.mark.asyncio
async def test_engine_cross_model_draft_matches_plain(tmp_path, monkeypatch):
  """XOT_TPU_SPEC_DRAFT=<dir> (VERDICT r4 #3): a SMALLER on-disk checkpoint
  drafts for the injected target — output must be the target's exact plain
  greedy stream (the draft only changes speed), and the engine must record
  the draft's own cfg/shard (its cache has the draft's geometry)."""
  from tests.test_hf_golden import _save_tiny_hf

  from xotorch_support_jetson_tpu.inference.jax_engine import JaxShardedInferenceEngine

  _save_tiny_hf(tmp_path, "llama")  # 2-layer dim-64 vocab-128 draft on disk
  cfg = tiny_test_config(n_layers=4, vocab_size=128, max_seq_len=128)
  params, shard = full_model_params(jax.random.PRNGKey(11), cfg, "m")
  prompt = np.array([[5, 9, 2, 71, 33]], dtype=np.int32)

  plain = JaxShardedInferenceEngine(use_local_mesh=False)
  plain.load_test_model(shard, cfg, params)
  logits, _ = await plain.infer_tensor("a", shard, prompt)
  first = int(np.argmax(logits, -1)[0])
  ref = await plain.generate_oneshot("a", shard, first, 20, eos_ids=(-1,), temp=0.0)

  monkeypatch.setenv("XOT_TPU_SPEC_DRAFT", str(tmp_path))
  spec = JaxShardedInferenceEngine(use_local_mesh=False, spec_decode="int8")
  spec.load_test_model(shard, cfg, params)
  assert spec._draft_params is not None, "cross-model draft failed to load"
  assert spec._draft_cfg is not None and spec._draft_cfg.n_layers != cfg.n_layers
  logits2, _ = await spec.infer_tensor("a", shard, prompt)
  assert int(np.argmax(logits2, -1)[0]) == first
  got = await spec.generate_oneshot("a", shard, first, 20, eos_ids=(-1,), temp=0.0)
  assert got == ref


def test_engine_cross_model_draft_refuses_vocab_mismatch(tmp_path, monkeypatch):
  """A draft whose vocab differs from the target's proposes ids the target
  cannot verify — the engine must refuse it at load, not mistranslate."""
  from tests.test_hf_golden import _save_tiny_hf

  from xotorch_support_jetson_tpu.inference.jax_engine import JaxShardedInferenceEngine

  _save_tiny_hf(tmp_path, "llama")  # vocab 128
  cfg = tiny_test_config(n_layers=4, vocab_size=256, max_seq_len=128)  # vocab 256 target
  params, shard = full_model_params(jax.random.PRNGKey(11), cfg, "m")

  monkeypatch.setenv("XOT_TPU_SPEC_DRAFT", str(tmp_path))
  spec = JaxShardedInferenceEngine(use_local_mesh=False, spec_decode="int8")
  spec.load_test_model(shard, cfg, params)
  assert spec._draft_params is None, "vocab-mismatched draft must be refused"


@pytest.mark.asyncio
async def test_solo_adaptive_gamma_collapses_to_plain_on_bad_draft():
  """ISSUE 7 satellite: an adversarial (near-zero-acceptance) draft must
  drive the solo path's acceptance EWMA down until gamma hits 0 — from then
  on dispatches take the PLAIN chunk program (XOT_TPU_SPEC_DECODE can never
  keep decoding slower than plain decode), and the stream stays exactly the
  plain greedy stream throughout the transition."""
  from xotorch_support_jetson_tpu.inference.jax_engine import JaxShardedInferenceEngine

  cfg = tiny_test_config(n_layers=4, max_seq_len=512)
  params, shard = full_model_params(jax.random.PRNGKey(11), cfg, "m")
  prompt = np.array([[5, 9, 2, 71, 33]], dtype=np.int32)

  plain = JaxShardedInferenceEngine(use_local_mesh=False, max_seq_len=512)
  plain.load_test_model(shard, cfg, params)
  logits, _ = await plain.infer_tensor("a", shard, prompt)
  first = int(np.argmax(logits, -1)[0])
  ref = [first]
  pending = await plain.dispatch_chunk("a", shard, 8, 0.0, 35, first_token=first)
  for _ in range(20):
    nxt = await plain.dispatch_chunk("a", shard, 8, 0.0, 35)
    ref.extend(await plain.read_chunk(pending))
    pending = nxt
    if pending is None:
      break

  spec = JaxShardedInferenceEngine(use_local_mesh=False, max_seq_len=512, spec_decode="int8")
  spec.load_test_model(shard, cfg, params)
  # Adversarial draft: unrelated random weights — argmax agreement ~1/vocab.
  spec._draft_params = full_model_params(jax.random.PRNGKey(777), cfg, "m")[0]
  assert spec._spec_gamma_live == spec.spec_gamma
  logits2, _ = await spec.infer_tensor("b", shard, prompt)
  assert int(np.argmax(logits2, -1)[0]) == first
  got = [first]
  kinds = []
  pending = await spec.dispatch_chunk("b", shard, 8, 0.0, 35, first_token=first)
  for _ in range(20):
    kinds.append("spec" if isinstance(pending, tuple) else "plain")
    nxt = await spec.dispatch_chunk("b", shard, 8, 0.0, 35)
    got.extend(await spec.read_chunk(pending))
    pending = nxt
    if pending is None:
      break
  assert got == ref[: len(got)]
  assert spec._spec_gamma_live == 0, f"gamma never collapsed (ewma {spec._spec_ewma})"
  # The transition really happened: spec chunks first, plain chunks after.
  assert kinds[0] == "spec" and kinds[-1] == "plain", kinds
  assert kinds.index("plain") == len(kinds) - kinds[::-1].count("plain"), f"plain/spec interleaved after collapse: {kinds}"


@pytest.mark.asyncio
async def test_solo_adaptive_gamma_reprobes_after_plain_streak(monkeypatch):
  """Once collapsed to plain, the engine re-probes at gamma 1 after
  XOT_TPU_SPEC_REPROBE plain dispatches — a draft that starts paying again
  (here: the real self-draft swapped back in) re-earns its depth."""
  from xotorch_support_jetson_tpu.inference.jax_engine import JaxShardedInferenceEngine

  monkeypatch.setenv("XOT_TPU_SPEC_REPROBE", "3")
  cfg = tiny_test_config(n_layers=4, max_seq_len=512)
  params, shard = full_model_params(jax.random.PRNGKey(11), cfg, "m")
  prompt = np.array([[5, 9, 2, 71, 33]], dtype=np.int32)

  eng = JaxShardedInferenceEngine(use_local_mesh=False, max_seq_len=512, spec_decode="int8")
  eng.load_test_model(shard, cfg, params)
  eng._spec_gamma_live = 0  # collapsed earlier (simulated)
  # Spec entry happens fresh-after-prefill (the draft cache is prompt-deep),
  # so the plain streak accrues per REQUEST; after three plain requests the
  # fourth probes at gamma 1 and the healthy self-draft re-earns its depth.
  kinds = []
  for i in range(5):
    rid = f"r{i}"
    logits, _ = await eng.infer_tensor(rid, shard, prompt)
    first = int(np.argmax(logits, -1)[0])
    h = await eng.dispatch_chunk(rid, shard, 4, 0.0, 35, first_token=first)
    kinds.append("spec" if isinstance(h, tuple) else "plain")
    await eng.read_chunk(h)
    eng.end_request(rid)
  assert kinds[:3] == ["plain", "plain", "plain"], kinds
  assert "spec" in kinds[3:], kinds
  assert eng._spec_gamma_live >= 1


def test_engine_cross_model_draft_missing_dir_disables(monkeypatch):
  """A draft spec that resolves to no local checkpoint disables speculation
  with a log line — never a crash, never a surprise network download."""
  from xotorch_support_jetson_tpu.inference.jax_engine import JaxShardedInferenceEngine

  cfg = tiny_test_config(n_layers=4, max_seq_len=128)
  params, shard = full_model_params(jax.random.PRNGKey(11), cfg, "m")
  monkeypatch.setenv("XOT_TPU_SPEC_DRAFT", "no-such-model-anywhere")
  spec = JaxShardedInferenceEngine(use_local_mesh=False, spec_decode="int8")
  spec.load_test_model(shard, cfg, params)
  assert spec._draft_params is None
