"""Disaggregated prefill/decode suite (ISSUE 10).

Covers every acceptance point: the serialization raw-bytes fast path's
shape/dtype pin, the placement policy, the wire-adopt → restore cycle, the
``XOT_TPU_DISAGG=0`` byte-identity pin, and the REAL two-node gRPC fixture —
a request prefilled on node A and decoded on node B streams token-identical
to the single-node baseline (lookahead on AND off), and a decode target
killed mid-transfer falls back to a local resume with no hang.
"""

import asyncio

import numpy as np
import pytest

from xotorch_support_jetson_tpu.inference import sched_admission
from xotorch_support_jetson_tpu.networking.faults import FaultRule, chaos
from xotorch_support_jetson_tpu.networking.grpc import kv_stream_pb2 as pbkv
from xotorch_support_jetson_tpu.networking.grpc.serialization import (
  kv_pages_to_proto,
  proto_payload_bytes,
  proto_to_kv_pages,
  proto_to_tensor,
  tensor_to_proto,
)
from xotorch_support_jetson_tpu.networking.retry import breakers, peer_health
from xotorch_support_jetson_tpu.utils.metrics import metrics as gm

PROMPT = [3, 25, 9, 7, 1, 88, 42, 5, 100, 11, 60]  # 11 tokens: 2 full pages at ps=4


@pytest.fixture(autouse=True)
def _clean_cluster_state(monkeypatch):
  monkeypatch.setenv("XOT_TPU_RETRY_DELAY_S", "0.05")
  chaos.clear()
  breakers.reset()
  peer_health.reset()
  yield
  chaos.clear()
  breakers.reset()
  peer_health.reset()


# ------------------------------------------------- serialization fast path


def test_tensor_roundtrip_pins_shape_dtype_and_zero_copy_receive():
  """The raw-bytes fast path (ISSUE 10 satellite): contiguous int8/uint8
  arrays round-trip with shape/dtype exact, non-contiguous views serialize
  correctly WITHOUT the historical ascontiguousarray pre-copy, and the
  receive side is a zero-copy read-only view over the message buffer."""
  for dtype in (np.int8, np.uint8, np.int32, np.float32):
    a = np.arange(24, dtype=dtype).reshape(2, 3, 4)
    out = proto_to_tensor(tensor_to_proto(a))
    assert out.shape == a.shape and out.dtype == a.dtype
    assert np.array_equal(out, a)
    # Zero-copy receive: a frombuffer view, not an owning copy.
    assert out.base is not None and not out.flags.writeable
  # Non-contiguous view: tobytes() emits C-order bytes in one pass.
  base = np.arange(64, dtype=np.int8).reshape(8, 8)
  view = base[::2, 1::3]
  assert not view.flags.c_contiguous
  out = proto_to_tensor(tensor_to_proto(view))
  assert np.array_equal(out, np.ascontiguousarray(view))
  # bf16 survives end-to-end (the historical contract).
  import ml_dtypes

  b = np.arange(6, dtype=np.float32).astype(ml_dtypes.bfloat16).reshape(2, 3)
  out = proto_to_tensor(tensor_to_proto(b))
  assert out.dtype == b.dtype and np.array_equal(out.astype(np.float32), b.astype(np.float32))


def test_kv_page_batch_roundtrip_and_payload_accounting():
  """The KV-page stream message: leaves round-trip exactly (int8 codes are
  1 byte/element on the wire) and the batch is counted by
  ``proto_payload_bytes`` like every data-plane message."""
  keys = [b"\x01" * 16, b"\x02" * 16]
  leaves = {
    "k": np.arange(2 * 2 * 4 * 3, dtype=np.int8).reshape(2, 2, 4, 3),
    "k_scale": np.linspace(0, 1, 2 * 2 * 4, dtype=np.float32).reshape(2, 2, 4),
  }
  msg = kv_pages_to_proto("req-1", keys, leaves, page_size=4, seq=3, last=True, origin="nodeA")
  wire = msg.SerializeToString()
  back = pbkv.KvPageBatch.FromString(wire)
  assert back.request_id == "req-1" and back.seq == 3 and back.last and back.origin == "nodeA"
  out_keys, out_leaves = proto_to_kv_pages(back)
  assert out_keys == keys
  for name, arr in leaves.items():
    assert out_leaves[name].dtype == arr.dtype and out_leaves[name].shape == arr.shape
    assert np.array_equal(out_leaves[name], arr)
  payload = proto_payload_bytes(msg)
  raw = sum(a.nbytes for a in leaves.values())
  assert payload >= raw  # the int8 codes dominate and ride uninflated
  assert payload < raw + 1024  # framing overhead only — no base64-style blowup


# ----------------------------------------------------------- placement policy


def test_choose_decode_node_prefers_dedicated_role_then_free_pages():
  stats = {
    "d1": {"role": "decode", "free_pages": 10, "queue_depth": 3},
    "d2": {"role": "decode", "free_pages": 40, "queue_depth": 5},
    "b1": {"role": "both", "free_pages": 500, "queue_depth": 0},
    "p1": {"role": "prefill", "free_pages": 900, "queue_depth": 0},
  }
  # Dedicated decode nodes outrank 'both'; free pages orders within the tier.
  assert sched_admission.choose_decode_node(stats, self_id="me", self_role="prefill") == "d2"
  # A 'both' node only hands off to DEDICATED decode peers (no ping-pong).
  only_both = {"b1": {"role": "both", "free_pages": 500}, "b2": {"role": "both", "free_pages": 900}}
  assert sched_admission.choose_decode_node(only_both, self_id="b1", self_role="both") is None
  # A prefill node may fall back to a 'both' peer.
  assert sched_admission.choose_decode_node(only_both, self_id="me", self_role="prefill") == "b2"
  # Queue depth breaks free-page ties; self and prefill-only peers never match.
  tie = {
    "d1": {"role": "decode", "free_pages": 10, "queue_depth": 9},
    "d2": {"role": "decode", "free_pages": 10, "queue_depth": 1},
  }
  assert sched_admission.choose_decode_node(tie, self_id="d9", self_role="both") == "d2"
  # Unknown capacity (no advertised free_pages) ranks LAST within the tier:
  # a peer with a real pool must never lose to one that may not have one —
  # but an unknown-capacity peer still wins as the only candidate.
  unknown = {"d1": {"role": "decode"}, "d2": {"role": "decode", "free_pages": 3, "queue_depth": 9}}
  assert sched_admission.choose_decode_node(unknown, self_id="me", self_role="prefill") == "d2"
  assert sched_admission.choose_decode_node({"d1": {"role": "decode"}}, self_id="me", self_role="both") == "d1"
  assert sched_admission.choose_decode_node({}, self_id="me") is None


def test_choose_prefill_node_orders_by_queue_drain_estimate():
  stats = {
    "p1": {"role": "prefill", "est_drain_ms": 900.0, "queue_depth": 1},
    "p2": {"role": "prefill", "est_drain_ms": 30.0, "queue_depth": 8},
    "b1": {"role": "both", "est_drain_ms": 1.0, "queue_depth": 0},
    "d1": {"role": "decode", "est_drain_ms": 0.0, "queue_depth": 0},
  }
  # Dedicated prefill nodes outrank 'both'; the drain estimate orders them.
  assert sched_admission.choose_prefill_node(stats, self_id="me") == "p2"
  # Decode-only peers are never prefill targets.
  assert sched_admission.choose_prefill_node({"d1": {"role": "decode"}}, self_id="me") is None
  # Without estimates, queue depth orders (scaled as a pseudo-estimate).
  cold = {"p1": {"role": "prefill", "queue_depth": 5}, "p2": {"role": "prefill", "queue_depth": 1}}
  assert sched_admission.choose_prefill_node(cold, self_id="me") == "p2"


def test_role_and_disagg_env_defaults(monkeypatch):
  monkeypatch.delenv("XOT_TPU_ROLE", raising=False)
  monkeypatch.delenv("XOT_TPU_DISAGG", raising=False)
  assert sched_admission.node_role() == "both"
  assert not sched_admission.disagg_enabled()  # unset = colocated, byte-identical
  monkeypatch.setenv("XOT_TPU_ROLE", "PREFILL ")
  assert sched_admission.node_role() == "prefill"
  monkeypatch.setenv("XOT_TPU_ROLE", "nonsense")
  assert sched_admission.node_role() == "both"  # unrecognized degrades safely
  monkeypatch.setenv("XOT_TPU_DISAGG", "0")
  assert not sched_admission.disagg_enabled()
  monkeypatch.setenv("XOT_TPU_DISAGG", "1")
  assert sched_admission.disagg_enabled()


# ---------------------------------------------------------- wire adoption unit


def test_adopt_wire_geometry_guard_and_budget(monkeypatch):
  """adopt_wire stores per-page host entries in the restore layout, refuses
  foreign geometry (mixing layouts would poison later restores), and the
  byte budget still evicts."""
  from xotorch_support_jetson_tpu.inference.kv_tier import KvTierManager

  tier = KvTierManager(page_size=4, read_pages=lambda p: (None, 0), write_pages=lambda p, d: None, budget_bytes=1 << 20)
  keys = [bytes([i]) * 16 for i in range(3)]
  leaves = {"k": np.arange(2 * 3 * 4, dtype=np.int8).reshape(2, 3, 4)}
  assert tier.adopt_wire(keys, leaves) == 3
  assert tier.host_pages == 3 and all(tier.host_has(k) for k in keys)
  per_page = 2 * 4  # [L=2, ps-dim 4] int8
  assert tier.host_bytes == 3 * per_page
  # Restore layout: host_run finds the contiguous run.
  assert tier.host_run(keys, 0, 3) == keys
  # Foreign geometry refused, store untouched.
  assert tier.adopt_wire([b"\xaa" * 16], {"k": np.zeros((2, 1, 9), np.int8)}) == 0
  assert tier.host_pages == 3
  # Budget pressure evicts oldest entries (adopted pages are plain entries).
  small = KvTierManager(page_size=4, read_pages=lambda p: (None, 0), write_pages=lambda p, d: None, budget_bytes=2 * per_page)
  assert small.adopt_wire(keys, leaves) == 3
  assert small.host_pages == 2 and small.host_bytes <= 2 * per_page


# ------------------------------------------------------ DISAGG=0 identity pin


def test_disagg_off_never_consults_placement(monkeypatch):
  """XOT_TPU_DISAGG unset/0 is byte-identical to the colocated scheduler:
  the placement policy is never consulted, no request carries a disagg
  target, and the stream matches the solo greedy reference."""
  import jax

  from xotorch_support_jetson_tpu.inference.jax_engine import JaxShardedInferenceEngine
  from tests.test_batched import CFG, KEY, _single_row_reference
  from xotorch_support_jetson_tpu.models.decoder import full_model_params

  monkeypatch.delenv("XOT_TPU_DISAGG", raising=False)

  def poisoned(*a, **k):  # noqa: ANN001
    raise AssertionError("placement consulted with XOT_TPU_DISAGG off")

  monkeypatch.setattr(sched_admission, "choose_decode_node", poisoned)
  monkeypatch.setattr(sched_admission, "choose_prefill_node", poisoned)

  params, shard = full_model_params(KEY, CFG, "m")
  engine = JaxShardedInferenceEngine(use_local_mesh=False)
  engine.load_test_model(shard, CFG, params)
  n = 8
  expected = _single_row_reference(params, shard, PROMPT, n - 1)
  server = engine.get_batched_server()
  try:
    got = asyncio.run(server.submit(
      "off-req", np.asarray(PROMPT, np.int32), max_tokens=n, temp=0.0, top_k=35, eos_ids=(), emit=lambda *_: None,
    ))
    assert got == expected
    assert all(s is None for s in server.slots)
  finally:
    server.shutdown()


# ------------------------------------------------------- two-node gRPC fixture


async def _make_disagg_cluster(monkeypatch, ids, ports):
  """Two full-model jax nodes on a localhost gRPC ring: node 0 = prefill,
  node 1 = decode (roles overridden per node — both share the process env)."""
  from xotorch_support_jetson_tpu.inference.jax_engine import JaxShardedInferenceEngine
  from xotorch_support_jetson_tpu.networking.grpc.grpc_peer_handle import GRPCPeerHandle
  from xotorch_support_jetson_tpu.networking.grpc.grpc_server import GRPCServer
  from xotorch_support_jetson_tpu.orchestration.node import Node
  from xotorch_support_jetson_tpu.topology.partitioning import RingMemoryWeightedPartitioningStrategy
  from tests.test_batched import CFG, KEY
  from tests.test_networking import CAPS, StaticDiscovery
  from xotorch_support_jetson_tpu.models.decoder import full_model_params

  class _Tok:
    eos_token_id = None

    def encode(self, prompt):
      return list(PROMPT)

    def decode(self, toks):
      return " ".join(map(str, toks))

  params, shard = full_model_params(KEY, CFG, "m")
  nodes = []
  for i in range(2):
    engine = JaxShardedInferenceEngine(use_local_mesh=False)
    engine.load_test_model(shard, CFG, params, tokenizer=_Tok())
    peers = [GRPCPeerHandle(ids[j], f"127.0.0.1:{ports[j]}", "test", CAPS) for j in range(2) if j != i]
    node = Node(
      ids[i], None, engine, StaticDiscovery(peers), None,
      RingMemoryWeightedPartitioningStrategy(), max_generate_tokens=200, default_sample_temp=0.0,
    )
    node.server = GRPCServer(node, "127.0.0.1", ports[i])
    node.disagg_role = "prefill" if i == 0 else "decode"
    nodes.append(node)
  await asyncio.gather(*(n.start() for n in nodes))
  for _ in range(100):
    if all(len(n.topology.nodes) == 2 for n in nodes):
      break
    await asyncio.gather(*(n.collect_topology(set()) for n in nodes))
    await asyncio.sleep(0.05)
  return nodes, params, shard


def _disagg_env(monkeypatch, lookahead: bool):
  monkeypatch.setenv("XOT_TPU_DISAGG", "1")
  monkeypatch.setenv("XOT_TPU_PAGE_SIZE", "4")  # 11-token prompt → 2 full pages
  monkeypatch.setenv("XOT_TPU_PREFILL_CHUNK", "8")  # 2 chunks: transfer overlaps prefill
  monkeypatch.setenv("XOT_TPU_BATCH_CHUNK", "2")
  monkeypatch.setenv("XOT_TPU_SCHED_LOOKAHEAD", "1" if lookahead else "0")


async def _drive_disagg_request(nodes, shard, rid, n_tokens, timeout=90):
  collected: list[int] = []
  done = asyncio.Event()

  def on_tok(r, toks, fin):
    if r != rid:
      return
    collected.extend(toks)
    if fin:
      done.set()

  nodes[0].set_request_options(rid, max_tokens=n_tokens, temperature=0.0)
  nodes[0].on_token.register(f"disagg-{rid}").on_next(on_tok)
  serve = asyncio.ensure_future(nodes[0]._batched_serve(shard, shard, "prompt", rid))
  await asyncio.wait_for(done.wait(), timeout=timeout)
  await asyncio.wait_for(serve, timeout=timeout)
  return collected


@pytest.mark.asyncio
@pytest.mark.parametrize("lookahead", [True, False], ids=["lookahead", "sync"])
async def test_two_node_disagg_stream_token_identical(monkeypatch, lookahead):
  """Acceptance (ISSUE 10): a request prefilled on node A and decoded on
  node B streams token-identical to the single-node colocated baseline;
  the KV pages crossed the wire and B's admission restore-adopted them."""
  from xotorch_support_jetson_tpu.utils.helpers import find_available_port
  from tests.test_batched import _single_row_reference

  _disagg_env(monkeypatch, lookahead)
  ports = [find_available_port("127.0.0.1") for _ in range(2)]
  ids = [f"dis{'la' if lookahead else 'sy'}0", f"dis{'la' if lookahead else 'sy'}1"]
  nodes, params, shard = await _make_disagg_cluster(monkeypatch, ids, ports)
  try:
    n_tokens = 12
    expected = _single_row_reference(params, shard, PROMPT, n_tokens - 1)
    streamed_before = gm.counter_value("kv_stream_pages_total")
    adopted_before = gm.counter_value("kv_stream_adopted_pages_total")
    handoffs_before = gm.counter_value("disagg_handoffs_total")
    restored_before = gm.counter_value("kv_tier_restored_pages_total")

    rid = f"disagg-req-{ids[0]}"
    collected = await _drive_disagg_request(nodes, shard, rid, n_tokens)

    assert collected == expected
    # The handoff really happened and the pages really crossed the wire.
    assert gm.counter_value("disagg_handoffs_total") == handoffs_before + 1
    assert gm.counter_value("kv_stream_pages_total") >= streamed_before + 2
    assert gm.counter_value("kv_stream_adopted_pages_total") >= adopted_before + 2
    # B's admission extended its prefix hit from the adopted pages instead
    # of recomputing the full prefill.
    assert gm.counter_value("kv_tier_restored_pages_total") >= restored_before + 2
    # The decode node's scheduler (not A's) ran the decode chunks.
    srv_b = nodes[1].inference_engine.get_batched_server()
    assert all(s is None for s in srv_b.slots)  # finished clean
    # Timeline carries the disagg stages on the prefill node.
    from xotorch_support_jetson_tpu.orchestration.tracing import tracer

    tl = tracer.timeline_export(rid) or {}
    stages = {e.get("stage") for e in tl.get("events", [])}
    assert "disagg_handoff" in stages and "kv_stream" in stages
  finally:
    for n in nodes:
      await n.stop()


@pytest.mark.asyncio
async def test_decode_target_killed_mid_transfer_falls_back_locally(monkeypatch):
  """Acceptance (ISSUE 10): the decode target dies after the first KV batch
  but before the handoff — the prefill node resumes locally via
  carry_tokens, the stream finishes token-identical, and nothing hangs.
  A dead decode target must never strand a prefilled context."""
  from xotorch_support_jetson_tpu.utils.helpers import find_available_port
  from tests.test_batched import _single_row_reference

  _disagg_env(monkeypatch, True)
  ports = [find_available_port("127.0.0.1") for _ in range(2)]
  ids = ["diskill0", "diskill1"]
  nodes, params, shard = await _make_disagg_cluster(monkeypatch, ids, ports)
  try:
    # Prime the placement cache while the target is still healthy, THEN
    # darken it: later KV batches and the handoff SendTensor both fail.
    await nodes[0].collect_disagg_stats(timeout=2.0)
    assert ids[1] in nodes[0]._disagg_stats
    chaos.install(FaultRule(peer=ids[1], method="SendKvPages", kind="error", after=1))
    chaos.install(FaultRule(peer=ids[1], method="SendTensor", kind="error"))

    n_tokens = 10
    expected = _single_row_reference(params, shard, PROMPT, n_tokens - 1)
    admissions_before = gm.counter_value("scheduler_admissions_total")
    rid = "disagg-kill-req"
    collected = await _drive_disagg_request(nodes, shard, rid, n_tokens, timeout=90)

    assert collected == expected
    # The fallback re-admitted the extracted row locally (initial admission
    # + carry_tokens resume), and A's pool fully recovered.
    assert gm.counter_value("scheduler_admissions_total") >= admissions_before + 2
    srv_a = nodes[0].inference_engine.get_batched_server()
    assert all(s is None for s in srv_a.slots)
    assert not srv_a.busy()
  finally:
    chaos.clear()
    for n in nodes:
      await n.stop()


@pytest.mark.asyncio
async def test_disagg_api_endpoint(monkeypatch):
  """GET /v1/disagg surfaces the disaggregation state: role, enabled flag,
  the cached peer adverts placement reads, and the transfer totals."""
  from aiohttp.test_utils import TestClient, TestServer

  from tests_support_stubs import NoDiscovery, StubServer
  from xotorch_support_jetson_tpu.api.chatgpt_api import ChatGPTAPI
  from xotorch_support_jetson_tpu.inference.dummy_engine import DummyInferenceEngine
  from xotorch_support_jetson_tpu.orchestration.node import Node
  from xotorch_support_jetson_tpu.topology.partitioning import RingMemoryWeightedPartitioningStrategy

  monkeypatch.setenv("XOT_TPU_DISAGG", "1")
  monkeypatch.setenv("XOT_TPU_ROLE", "prefill")
  node = Node("disagg-api-node", StubServer(), DummyInferenceEngine(), NoDiscovery(), None, RingMemoryWeightedPartitioningStrategy())
  await node.start()
  api = ChatGPTAPI(node, "DummyInferenceEngine", default_model="dummy")
  client = TestClient(TestServer(api.app))
  await client.start_server()
  try:
    resp = await client.get("/v1/disagg")
    assert resp.status == 200
    body = await resp.json()
    assert body["enabled"] is True and body["role"] == "prefill"
    assert set(body) >= {"local", "peers", "handoffs_total", "kv_stream_pages_total", "kv_stream_bytes_total", "kv_stream_adopted_pages_total"}
    assert body["local"]["role"] == "prefill"
    # The role gauge landed at node start: 1 = prefill.
    assert gm.gauges.get("node_role") == 1
    # scope=cluster with no peers degrades gracefully.
    resp = await client.get("/v1/disagg?scope=cluster")
    assert resp.status == 200
  finally:
    await client.close()
    await node.stop()
