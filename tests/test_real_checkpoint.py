"""Real-checkpoint golden smoke (network-gated; VERDICT r1 weak #6).

The HF golden tests (tests/test_hf_golden.py) run tiny RANDOM checkpoints —
perfect for layout/math parity, blind to config-field drift HF occasionally
ships in real repos. This test downloads the smallest real registry model
(qwen-2.5-0.5b), asserts logit parity against transformers, and runs one
chat-templated generation through the engine's own loader path.

Skips when the hub is unreachable (HF_HUB_OFFLINE, no egress, or the
download fails) — the CI image has no network; run it wherever egress
exists: ``pytest tests/test_real_checkpoint.py -m ''``.
"""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

MODEL_ID = "qwen-2.5-0.5b"
REPO = "unsloth/Qwen2.5-0.5B-Instruct"


def _fetch_model():
  if os.getenv("HF_HUB_OFFLINE") == "1":
    pytest.skip("hub offline (HF_HUB_OFFLINE=1)")
  try:
    from huggingface_hub import snapshot_download

    return snapshot_download(REPO, allow_patterns=["*.json", "*.safetensors", "tokenizer*", "*.txt"])
  except Exception as e:  # noqa: BLE001 — no egress / rate limit / auth
    pytest.skip(f"cannot download {REPO}: {e}")


def test_real_checkpoint_logits_and_chat_generation():
  path = _fetch_model()

  from transformers import AutoModelForCausalLM, AutoTokenizer

  from xotorch_support_jetson_tpu.inference.shard import Shard
  from xotorch_support_jetson_tpu.models.config import load_model_config
  from xotorch_support_jetson_tpu.models.decoder import shard_forward
  from xotorch_support_jetson_tpu.models.loader import load_shard_weights

  cfg = load_model_config(path, dtype=jnp.float32)
  shard = Shard(MODEL_ID, 0, cfg.n_layers - 1, cfg.n_layers)
  params = load_shard_weights(path, cfg, shard)

  tok = AutoTokenizer.from_pretrained(path)
  msgs = [{"role": "user", "content": "What is 2+2?"}]
  ids = tok.apply_chat_template(msgs, add_generation_prompt=True, return_tensors="np").astype(np.int32)

  # Logit parity vs transformers at f32.
  import torch

  hf = AutoModelForCausalLM.from_pretrained(path, torch_dtype=torch.float32).eval()
  with torch.no_grad():
    ref = hf(torch.from_numpy(ids.astype(np.int64))).logits.numpy()

  positions = np.broadcast_to(np.arange(ids.shape[1], dtype=np.int32), ids.shape)
  with jax.default_matmul_precision("highest"):
    logits, _ = shard_forward(params, cfg, shard, jnp.asarray(ids), jnp.asarray(positions), None)
  got = np.asarray(logits)
  # Real-weight logits are O(10); compare top-candidate agreement + rtol.
  np.testing.assert_allclose(got[0, -1], ref[0, -1], rtol=2e-3, atol=2e-3)
  assert int(np.argmax(got[0, -1])) == int(np.argmax(ref[0, -1]))

  # One greedy chat generation end-to-end through the cached decode path.
  from xotorch_support_jetson_tpu.models.decoder import fused_decode, init_kv_cache

  S = ids.shape[1]
  cache = init_kv_cache(cfg, cfg.n_layers, 1, S + 32)
  logits, cache = shard_forward(params, cfg, shard, jnp.asarray(ids), jnp.asarray(positions), cache)
  first = jnp.argmax(logits[:, -1, :], axis=-1).astype(jnp.int32)[:, None]
  toks, _ = fused_decode(params, cfg, shard, first, cache, jnp.full((1,), S, jnp.int32), 16)
  text = tok.decode([int(first[0, 0])] + [int(t) for t in np.asarray(toks)[0]])
  assert "4" in text, f"0.5B chat model failed 2+2: {text!r}"
