"""Sequence-parallel serving tests (parallel/sp_serving.py).

Correctness claim: prefill + decode with the KV cache sharded over sp (and
partial online-softmax stats merged over the axis) are TOKEN-IDENTICAL to the
single-device engine — for dense GQA and for MLA (the absorbed-attention
merge composes with sp because the per-head up-projection applies after the
cross-rank merge; this closes the round-1 "ring attention is training-only
and doesn't compose with MLA" gap for the serving side).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from xotorch_support_jetson_tpu.models.config import tiny_test_config
from xotorch_support_jetson_tpu.models.decoder import full_model_params, fused_decode, init_kv_cache, shard_forward
from xotorch_support_jetson_tpu.parallel.mesh import MeshPlan, build_mesh
from xotorch_support_jetson_tpu.parallel.sp_serving import SPServing

DENSE = tiny_test_config(n_layers=2, max_seq_len=128)
MLA = tiny_test_config(
  n_layers=2, max_seq_len=128, n_heads=4, n_kv_heads=4, kv_lora_rank=16,
  q_lora_rank=24, qk_nope_head_dim=16, qk_rope_head_dim=8, v_head_dim=16,
)
GEMMA = tiny_test_config(
  n_layers=2, max_seq_len=128, post_norms=True, mlp_act="gelu_tanh",
  attn_logit_softcap=50.0, final_logit_softcap=30.0, query_pre_attn_scalar=24.0,
  sliding_window=4, embed_scale=8.0, tied_embedding=True,
)


def _reference(params, cfg, shard, prompt, n_steps):
  S = len(prompt)
  tokens = jnp.asarray([prompt], jnp.int32)
  positions = jnp.broadcast_to(jnp.arange(S, dtype=jnp.int32), (1, S))
  cache = init_kv_cache(cfg, cfg.n_layers, 1, 64)
  logits, cache = shard_forward(params, cfg, shard, tokens, positions, cache)
  first = jnp.argmax(logits[:, -1, :], axis=-1).astype(jnp.int32)[:, None]
  toks, _ = fused_decode(params, cfg, shard, first, cache, jnp.full((1,), S, jnp.int32), n_steps)
  return int(first[0, 0]), np.asarray(toks)[0]


@pytest.mark.parametrize("cfg,sp_n", [(DENSE, 2), (DENSE, 4), (MLA, 2), (MLA, 4), (GEMMA, 2)])
def test_sp_serving_matches_single_device(cfg, sp_n):
  params, shard = full_model_params(jax.random.PRNGKey(0), cfg, "tiny")
  prompt = [3, 25, 9, 77, 2]
  S = len(prompt)
  first_ref, ref = _reference(params, cfg, shard, prompt, 10)

  mesh = build_mesh(MeshPlan(sp=sp_n))
  sps = SPServing(mesh, cfg, params, sp_n, True, True)
  cache = sps.place_cache(init_kv_cache(cfg, cfg.n_layers, 1, 64))
  tok_pad = np.zeros((1, 8), np.int32)
  tok_pad[0, :S] = prompt
  last, cache = sps.prefill(jnp.asarray(tok_pad), cache, jnp.full((1,), S, jnp.int32))
  first = jnp.argmax(last, axis=-1).astype(jnp.int32)[:, None]
  assert int(first[0, 0]) == first_ref
  toks, cache = sps.fused_decode(first, cache, jnp.full((1,), S, jnp.int32), 10)
  assert np.array_equal(np.asarray(toks)[0], ref)


def test_sp_fused_generate_and_decode_step_match():
  cfg = DENSE
  params, shard = full_model_params(jax.random.PRNGKey(1), cfg, "tiny")
  prompt = [7, 1, 88, 42]
  S = len(prompt)
  first_ref, ref = _reference(params, cfg, shard, prompt, 6)

  mesh = build_mesh(MeshPlan(sp=2))
  sps = SPServing(mesh, cfg, params, 2, True, True)
  tok_pad = np.zeros((1, 8), np.int32)
  tok_pad[0, :S] = prompt

  # fused_generate (while_loop path)
  cache = sps.place_cache(init_kv_cache(cfg, cfg.n_layers, 1, 64))
  last, cache = sps.prefill(jnp.asarray(tok_pad), cache, jnp.full((1,), S, jnp.int32))
  first = jnp.argmax(last, axis=-1).astype(jnp.int32)[:, None]
  buf, n, cache = sps.fused_generate(first, cache, jnp.full((1,), S, jnp.int32), 6, eos_ids=(-1,))
  assert np.array_equal(np.asarray(buf)[0][:6], ref)

  # per-step decode path
  cache = sps.place_cache(init_kv_cache(cfg, cfg.n_layers, 1, 64))
  last, cache = sps.prefill(jnp.asarray(tok_pad), cache, jnp.full((1,), S, jnp.int32))
  tok = jnp.argmax(last, axis=-1).astype(jnp.int32)[:, None]
  got = []
  pos = S
  for _ in range(6):
    logits, cache = sps.decode_step(tok, cache, jnp.full((1,), pos, jnp.int32))
    tok = jnp.argmax(logits, axis=-1).astype(jnp.int32)[:, None]
    got.append(int(tok[0, 0]))
    pos += 1
  assert got == [int(t) for t in ref]


def test_engine_sp_mode_serves(monkeypatch):
  """XOT_TPU_SP engine mode: the engine builds SPServing and the fused
  serving path matches the plain engine."""
  from tests_support_stubs import require_partial_manual

  require_partial_manual(MeshPlan(sp=2, tp=4), manual=("sp",))
  from xotorch_support_jetson_tpu.inference.jax_engine import JaxShardedInferenceEngine

  cfg = DENSE
  params, shard = full_model_params(jax.random.PRNGKey(2), cfg, "tiny")
  _, ref = _reference(params, cfg, shard, [5, 17, 2, 99], 7)

  monkeypatch.setenv("XOT_TPU_SP", "2")
  eng = JaxShardedInferenceEngine(use_local_mesh=False)
  eng.load_test_model(shard, cfg, jax.tree.map(jnp.copy, params))
  eng._maybe_shard_over_local_mesh()
  assert eng._pp is not None and eng.params is None  # SPServing rides the mesh-serving slot
  cache = eng._pp.place_cache(init_kv_cache(cfg, cfg.n_layers, 1, 64))
  tok_pad = np.zeros((1, 8), np.int32)
  tok_pad[0, :4] = [5, 17, 2, 99]
  last, cache = eng._pp.prefill(jnp.asarray(tok_pad), cache, jnp.full((1,), 4, jnp.int32))
  first = jnp.argmax(last, axis=-1).astype(jnp.int32)[:, None]
  toks, _ = eng._pp.fused_decode(first, cache, jnp.full((1,), 4, jnp.int32), 7)
  assert np.array_equal(np.asarray(toks)[0], ref)


def test_sp_decode_spans_all_rank_chunks():
  """Decode far past rank 0's chunk (sp=4, Sloc=16, 40 steps → position 51):
  writes land on every rank and non-masked partials from all ranks merge —
  still token-identical to the single-device decode."""
  cfg = DENSE
  params, shard = full_model_params(jax.random.PRNGKey(3), cfg, "tiny")
  prompt = [9, 9, 9, 1, 42, 7, 3, 25, 100, 2, 11]
  S = len(prompt)
  _, ref = _reference(params, cfg, shard, prompt, 40)

  mesh = build_mesh(MeshPlan(sp=4))
  sps = SPServing(mesh, cfg, params, 4, True, True)
  cache = sps.place_cache(init_kv_cache(cfg, cfg.n_layers, 1, 64))  # Sloc = 16
  tok_pad = np.zeros((1, 16), np.int32)
  tok_pad[0, :S] = prompt
  last, cache = sps.prefill(jnp.asarray(tok_pad), cache, jnp.full((1,), S, jnp.int32))
  first = jnp.argmax(last, axis=-1).astype(jnp.int32)[:, None]
  toks, _ = sps.fused_decode(first, cache, jnp.full((1,), S, jnp.int32), 40)
  assert np.array_equal(np.asarray(toks)[0], ref)


@pytest.mark.parametrize("cfg,plan", [
  (DENSE, MeshPlan(sp=2, tp=2)),
  (DENSE, MeshPlan(sp=2, tp=4)),
  (MLA, MeshPlan(sp=2, tp=2)),
  (GEMMA, MeshPlan(sp=2, tp=2)),
], ids=["dense-sp2tp2", "dense-sp2tp4", "mla-sp2tp2", "gemma-sp2tp2"])
def test_sp_tp_composed_matches_and_shards_weights(cfg, plan):
  from tests_support_stubs import require_partial_manual

  require_partial_manual(plan, manual=("sp",))
  """sp x tp composition (VERDICT r2 #3): weights shard over tp (per-rank
  weight bytes ~1/tp of replicated) while the cache shards over sp — and the
  decoded tokens still match the single device exactly."""
  params, shard = full_model_params(jax.random.PRNGKey(0), cfg, "tiny")
  prompt = [3, 25, 9, 77, 2]
  S = len(prompt)
  first_ref, ref = _reference(params, cfg, shard, prompt, 10)

  mesh = build_mesh(plan)
  sps = SPServing(mesh, cfg, params, plan.sp, True, True)
  # Megatron column-parallel wq: each device holds 1/tp of the leaf (and the
  # sp axis replicates it — the round-2 design held 1/1 on every rank).
  stack = sps.params["layers"]
  wq = stack["wq"] if "wq" in stack else stack["wq_b"]  # MLA: per-head up-proj is the column-parallel leaf
  assert wq.addressable_shards[0].data.nbytes == wq.nbytes // plan.tp
  # The cache shards over sp on the sequence axis.
  cache = sps.place_cache(init_kv_cache(cfg, cfg.n_layers, 1, 64))
  assert cache["k"].addressable_shards[0].data.shape[2] == 64 // plan.sp

  tok_pad = np.zeros((1, 8), np.int32)
  tok_pad[0, :S] = prompt
  last, cache = sps.prefill(jnp.asarray(tok_pad), cache, jnp.full((1,), S, jnp.int32))
  first = jnp.argmax(last, axis=-1).astype(jnp.int32)[:, None]
  assert int(first[0, 0]) == first_ref
  toks, cache = sps.fused_decode(first, cache, jnp.full((1,), S, jnp.int32), 10)
  assert np.array_equal(np.asarray(toks)[0], ref)


def test_sp_batched_decode_matches_single_device():
  from tests_support_stubs import require_partial_manual

  require_partial_manual(MeshPlan(sp=2, tp=2), manual=("sp",))
  """SP x batched composition (parallel/sp_batch.py): the slot pool's fused
  chunk decode with the cache sharded over sp is token-identical to the
  single-device fused_batch_decode — concurrent long-context streams."""
  from xotorch_support_jetson_tpu.models.decoder import fused_batch_decode, prefill_into_slot
  from xotorch_support_jetson_tpu.parallel.sp_batch import SPBatchedServing

  cfg = DENSE
  params, shard = full_model_params(jax.random.PRNGKey(21), cfg, "tiny")
  mesh = build_mesh(MeshPlan(sp=2, tp=2))
  spb = SPBatchedServing(SPServing(mesh, cfg, params, 2, True, True))

  prompts = [[3, 25, 9], [7, 1, 88, 42, 5], [100], [9, 9, 9, 1]]
  B, max_seq, n_steps = 4, 64, 6
  cache_ref = init_kv_cache(cfg, cfg.n_layers, B, max_seq)
  cache_sp = spb.place_cache(init_kv_cache(cfg, cfg.n_layers, B, max_seq))
  firsts_ref, firsts_sp = [], []
  for r, p in enumerate(prompts):
    pad = np.zeros((1, 16), np.int32)
    pad[0, : len(p)] = p
    last_r, cache_ref = prefill_into_slot(params, cfg, shard, jnp.asarray(pad), cache_ref, jnp.int32(r), jnp.int32(len(p)))
    last_s, cache_sp = spb.prefill_into_slot(jnp.asarray(pad), cache_sp, r, len(p))
    firsts_ref.append(int(np.argmax(np.asarray(last_r)[0])))
    firsts_sp.append(int(np.argmax(np.asarray(last_s)[0])))
  assert firsts_sp == firsts_ref

  tok = jnp.asarray([[f] for f in firsts_ref], jnp.int32)
  pos = jnp.asarray([len(p) for p in prompts], jnp.int32)
  active = jnp.asarray([True, True, False, True])
  temps = jnp.zeros((B,), jnp.float32)
  top_ks = jnp.full((B,), 35, jnp.int32)
  for _ in range(2):  # two chained chunks: writes land where the next reads
    ref_toks, _, pos_ref, cache_ref = fused_batch_decode(params, cfg, shard, tok, cache_ref, pos, active, temps, n_steps)
    sp_toks, _, pos_sp, cache_sp = spb.batch_decode(tok, cache_sp, pos, active, temps, top_ks, n_steps)
    np.testing.assert_array_equal(np.asarray(sp_toks), np.asarray(ref_toks))
    np.testing.assert_array_equal(np.asarray(pos_sp), np.asarray(pos_ref))
    tok = jnp.asarray(np.asarray(ref_toks)[:, -1:])
    pos = pos_ref


def test_sp_batched_through_scheduler(monkeypatch):
  from tests_support_stubs import require_partial_manual

  require_partial_manual(MeshPlan(sp=2, tp=4), manual=("sp",))
  """End-to-end: an XOT_TPU_SP=2 engine's batch scheduler (dense cache,
  XOT_TPU_PAGED=0) serves concurrent requests token-identically to solo
  runs. (The default paged mode composes too — tests/test_sp_paged.py.)"""
  import asyncio

  from tests.test_batched import _single_row_reference
  from xotorch_support_jetson_tpu.inference.batch_scheduler import BatchedServer
  from xotorch_support_jetson_tpu.inference.jax_engine import JaxShardedInferenceEngine

  monkeypatch.setenv("XOT_TPU_SP", "2")
  monkeypatch.setenv("XOT_TPU_PAGED", "0")
  cfg = DENSE
  params, shard = full_model_params(jax.random.PRNGKey(23), cfg, "tiny")
  engine = JaxShardedInferenceEngine(use_local_mesh=True)
  engine.load_test_model(shard, cfg, params)
  engine._maybe_shard_over_local_mesh()
  assert isinstance(engine._pp, SPServing)
  assert engine.supports_batched()
  monkeypatch.setenv("XOT_TPU_PAGED", "1")
  assert engine.supports_batched()  # striped paged pool composes with sp now
  monkeypatch.setenv("XOT_TPU_PAGED", "0")

  server = BatchedServer(engine, n_slots=4, chunk=2)
  prompts = [[3, 25, 9], [7, 1, 88, 42, 5], [100], [9, 9, 9, 1]]
  n_gen = 5
  expected = [_single_row_reference(params, shard, p, n_gen - 1, cfg=cfg) for p in prompts]

  async def run():
    return await asyncio.gather(
      *(
        server.submit(f"sp{i}", np.asarray(p, np.int32), max_tokens=n_gen, temp=0.0, top_k=35, eos_ids=(), emit=lambda *_: None)
        for i, p in enumerate(prompts)
      )
    )

  outs = asyncio.run(run())
  for i, out in enumerate(outs):
    assert out == expected[i], f"req {i}: {out} != {expected[i]}"


def test_supports_batched_requires_full_model_shard(monkeypatch):
  """A ring member serving a partial layer range must NOT route into the
  batched mesh paths (they embed tokens and run the head): supports_batched
  returns False so the Node falls back to plain mesh serving."""
  from xotorch_support_jetson_tpu.inference.jax_engine import JaxShardedInferenceEngine
  from xotorch_support_jetson_tpu.inference.shard import Shard

  monkeypatch.setenv("XOT_TPU_SP", "2")
  monkeypatch.setenv("XOT_TPU_PAGED", "0")
  cfg = DENSE
  params, full = full_model_params(jax.random.PRNGKey(29), cfg, "tiny")
  from xotorch_support_jetson_tpu.models.decoder import slice_shard_params

  partial = Shard("tiny", 1, cfg.n_layers - 1, cfg.n_layers)  # last but not first
  engine = JaxShardedInferenceEngine(use_local_mesh=True)
  engine.load_test_model(partial, cfg, slice_shard_params(params, cfg, full, partial))
  engine._maybe_shard_over_local_mesh()
  assert isinstance(engine._pp, SPServing) and not engine._pp.is_first
  assert not engine.supports_batched()
