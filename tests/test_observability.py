"""Tracing + metrics: live (unlike the reference's dead tracer, SURVEY §5.1)."""

import asyncio

import pytest

from xotorch_support_jetson_tpu.orchestration.tracing import (
  Tracer,
  format_traceparent,
  parse_traceparent,
)
from xotorch_support_jetson_tpu.utils.metrics import Metrics


def test_traceparent_roundtrip():
  tp = format_traceparent("a" * 32, "b" * 16)
  assert parse_traceparent(tp) == ("a" * 32, "b" * 16)
  assert parse_traceparent("garbage") is None
  assert parse_traceparent(None) is None


def test_span_lifecycle_and_token_groups():
  tracer = Tracer()
  ctx = tracer.request_context("req1")
  with tracer.start_span("request.process_prompt", "req1", {"model": "m"}) as span:
    assert span.trace_id == ctx.trace_id
  for _ in range(25):
    tracer.handle_token("req1")
  spans = tracer.recent_spans()
  names = [s["name"] for s in spans]
  assert "request.process_prompt" in names
  assert names.count("token_group") == 2  # groups of 10; 25 tokens → 2 full groups
  group = [s for s in spans if s["name"] == "token_group"][0]
  assert group["parent_id"] == ctx.request_span_id
  tracer.end_request("req1")
  assert "req1" not in tracer.contexts


def test_remote_context_joins_trace():
  tracer = Tracer()
  remote_tp = format_traceparent("c" * 32, "d" * 16)
  ctx = tracer.request_context("req2", remote_tp)
  assert ctx.trace_id == "c" * 32
  assert ctx.parent_id == "d" * 16


def test_metrics_render():
  m = Metrics()
  m.inc("requests_total")
  m.inc("requests_total", 2)
  m.set_gauge("active_sessions", 3)
  with m.timer("prefill"):
    pass
  text = m.render_prometheus()
  assert "xot_tpu_requests_total 3.0" in text
  assert "xot_tpu_active_sessions 3" in text
  assert "xot_tpu_prefill_seconds_count 1" in text


@pytest.mark.asyncio
async def test_node_generates_spans_and_metrics():
  from xotorch_support_jetson_tpu.inference.dummy_engine import DummyInferenceEngine
  from xotorch_support_jetson_tpu.orchestration.node import Node
  from xotorch_support_jetson_tpu.orchestration.tracing import tracer as global_tracer
  from xotorch_support_jetson_tpu.registry import build_base_shard
  from xotorch_support_jetson_tpu.topology.partitioning import RingMemoryWeightedPartitioningStrategy
  from xotorch_support_jetson_tpu.utils.metrics import metrics as global_metrics
  from tests_support_stubs import NoDiscovery, StubServer

  node = Node("trace-node", StubServer(), DummyInferenceEngine(), NoDiscovery(), None, RingMemoryWeightedPartitioningStrategy(), max_generate_tokens=30)
  await node.start()
  done = asyncio.Event()
  node.on_token.register("t").on_next(lambda r, toks, fin: done.set() if fin else None)
  before_tokens = global_metrics.counters["tokens_generated_total"]
  await node.process_prompt(build_base_shard("dummy", "DummyInferenceEngine"), "aaaa", "trace-req")
  await asyncio.wait_for(done.wait(), timeout=10)
  await node.stop()

  assert global_metrics.counters["tokens_generated_total"] > before_tokens
  names = [s["name"] for s in global_tracer.recent_spans(500)]
  assert "request.process_prompt" in names
  assert "token_group" in names
