"""Tracing + metrics: live (unlike the reference's dead tracer, SURVEY §5.1).

ISSUE 2 coverage: histogram bucket math and quantile edge cases, scheduler
gauge/counter lifecycle under admit/evict/grow, decode-path attribution
against the ``select_decode_path`` dispatch table, per-request stage
timelines (+ the ``/v1/requests/{id}/timeline`` endpoint and slow-request
log), the buffered-export / residual-token-group tracer fixes, cluster
snapshot merging, and a metric-name snapshot so the ``/metrics`` exposition
stays stable.
"""

import asyncio
import json

import pytest

from xotorch_support_jetson_tpu.orchestration.tracing import (
  Tracer,
  format_traceparent,
  parse_traceparent,
)
from xotorch_support_jetson_tpu.utils.metrics import Metrics


def test_traceparent_roundtrip():
  tp = format_traceparent("a" * 32, "b" * 16)
  assert parse_traceparent(tp) == ("a" * 32, "b" * 16)
  assert parse_traceparent("garbage") is None
  assert parse_traceparent(None) is None


def test_parse_traceparent_hardened():
  """Hardened parsing (ISSUE 4 satellite): any 4-dash-part string used to be
  accepted — garbage ids were silently adopted as trace identity."""
  good = f"00-{'a' * 32}-{'b' * 16}-01"
  assert parse_traceparent(good) is not None
  # Non-hex trace/span ids.
  assert parse_traceparent(f"00-{'g' * 32}-{'b' * 16}-01") is None
  assert parse_traceparent(f"00-{'a' * 32}-{'z' * 16}-01") is None
  # Uppercase hex is invalid per W3C (ids are lowercase base16).
  assert parse_traceparent(f"00-{'A' * 32}-{'b' * 16}-01") is None
  # All-zero ids are explicitly invalid.
  assert parse_traceparent(f"00-{'0' * 32}-{'b' * 16}-01") is None
  assert parse_traceparent(f"00-{'a' * 32}-{'0' * 16}-01") is None
  # Unknown/invalid version fields are rejected, not adopted.
  assert parse_traceparent(f"ff-{'a' * 32}-{'b' * 16}-01") is None
  assert parse_traceparent(f"01-{'a' * 32}-{'b' * 16}-01") is None
  assert parse_traceparent(f"xx-{'a' * 32}-{'b' * 16}-01") is None
  # Malformed flags / wrong lengths.
  assert parse_traceparent(f"00-{'a' * 32}-{'b' * 16}-zz") is None
  assert parse_traceparent(f"00-{'a' * 31}-{'b' * 16}-01") is None


def test_tracer_contexts_bounded():
  """A request cancelled/failed before end_request used to leave its
  TraceContext in the dict forever; the LRU cap bounds it (ISSUE 4
  satellite)."""
  from xotorch_support_jetson_tpu.orchestration import tracing

  t = Tracer()
  for i in range(tracing.MAX_CONTEXTS + 50):
    t.request_context(f"leak-{i}")  # never end_request'd
  assert len(t.contexts) == tracing.MAX_CONTEXTS
  assert "leak-0" not in t.contexts  # oldest evicted
  assert f"leak-{tracing.MAX_CONTEXTS + 49}" in t.contexts
  # Access refreshes recency: touching an old id keeps it past new inserts.
  t.request_context("leak-100")
  for i in range(200):
    t.request_context(f"leak2-{i}")
  assert "leak-100" in t.contexts


def test_span_lifecycle_and_token_groups():
  tracer = Tracer()
  ctx = tracer.request_context("req1")
  with tracer.start_span("request.process_prompt", "req1", {"model": "m"}) as span:
    assert span.trace_id == ctx.trace_id
  for _ in range(25):
    tracer.handle_token("req1")
  spans = tracer.recent_spans()
  names = [s["name"] for s in spans]
  assert "request.process_prompt" in names
  assert names.count("token_group") == 2  # groups of 10; 25 tokens → 2 full groups
  group = [s for s in spans if s["name"] == "token_group"][0]
  assert group["parent_id"] == ctx.request_span_id
  tracer.end_request("req1")
  assert "req1" not in tracer.contexts


def test_remote_context_joins_trace():
  tracer = Tracer()
  remote_tp = format_traceparent("c" * 32, "d" * 16)
  ctx = tracer.request_context("req2", remote_tp)
  assert ctx.trace_id == "c" * 32
  assert ctx.parent_id == "d" * 16


def test_metrics_render():
  m = Metrics()
  m.inc("requests_total")
  m.inc("requests_total", 2)
  m.set_gauge("active_sessions", 3)
  with m.timer("prefill"):
    pass
  text = m.render_prometheus()
  assert "xot_tpu_requests_total 3.0" in text
  assert "xot_tpu_active_sessions 3" in text
  assert "xot_tpu_prefill_seconds_count 1" in text


@pytest.mark.asyncio
async def test_node_generates_spans_and_metrics():
  from xotorch_support_jetson_tpu.inference.dummy_engine import DummyInferenceEngine
  from xotorch_support_jetson_tpu.orchestration.node import Node
  from xotorch_support_jetson_tpu.orchestration.tracing import tracer as global_tracer
  from xotorch_support_jetson_tpu.registry import build_base_shard
  from xotorch_support_jetson_tpu.topology.partitioning import RingMemoryWeightedPartitioningStrategy
  from xotorch_support_jetson_tpu.utils.metrics import metrics as global_metrics
  from tests_support_stubs import NoDiscovery, StubServer

  node = Node("trace-node", StubServer(), DummyInferenceEngine(), NoDiscovery(), None, RingMemoryWeightedPartitioningStrategy(), max_generate_tokens=30)
  await node.start()
  done = asyncio.Event()
  node.on_token.register("t").on_next(lambda r, toks, fin: done.set() if fin else None)
  before_tokens = global_metrics.counters["tokens_generated_total"]
  await node.process_prompt(build_base_shard("dummy", "DummyInferenceEngine"), "aaaa", "trace-req")
  await asyncio.wait_for(done.wait(), timeout=10)
  await node.stop()

  assert global_metrics.counters["tokens_generated_total"] > before_tokens
  names = [s["name"] for s in global_tracer.recent_spans(500)]
  assert "request.process_prompt" in names
  assert "token_group" in names


# ------------------------------------------------------------- histograms


def test_histogram_buckets_cumulative_exposition():
  m = Metrics()
  for v in (0.0005, 0.002, 0.02, 0.02, 0.3, 200.0):  # 200 s lands in +Inf
    m.observe_hist("ttft_seconds", v)
  text = m.render_prometheus()
  assert "# TYPE xot_tpu_ttft_seconds histogram" in text
  assert 'xot_tpu_ttft_seconds_bucket{le="0.001"} 1' in text  # cumulative
  assert 'xot_tpu_ttft_seconds_bucket{le="0.0025"} 2' in text
  assert 'xot_tpu_ttft_seconds_bucket{le="0.025"} 4' in text
  assert 'xot_tpu_ttft_seconds_bucket{le="+Inf"} 6' in text
  assert "xot_tpu_ttft_seconds_count 6" in text
  assert abs(float(text.split("xot_tpu_ttft_seconds_sum ")[1].split("\n")[0]) - 200.3425) < 1e-6


def test_histogram_quantile_edge_cases():
  m = Metrics()
  assert m.quantile("absent", 0.5) is None  # never created
  m.observe_hist("h", 0.02)
  # Single observation: every quantile lands inside its (0.01, 0.025] bucket.
  for q in (0.0, 0.5, 1.0):
    v = m.quantile("h", q)
    assert 0.01 <= v <= 0.025, (q, v)
  # +Inf landings clamp to the last finite edge (the histogram can't resolve
  # beyond its ladder).
  m2 = Metrics()
  m2.observe_hist("h", 1e9)
  assert m2.quantile("h", 0.99) == 60.0
  # Out-of-range q clamps instead of raising.
  assert m2.quantile("h", 7.0) == 60.0
  assert m2.quantile("h", -1.0) == 60.0
  # Interpolation: 100 uniform values in (0.01, 0.025] → median ≈ bucket mid.
  m3 = Metrics()
  for _ in range(100):
    m3.observe_hist("h", 0.02)
  v = m3.quantile("h", 0.5)
  assert 0.01 < v <= 0.025


def test_labeled_counters_and_gauges():
  m = Metrics()
  m.inc("decode_chunks_total", labels={"path": "kernel"})
  m.inc("decode_chunks_total", 2, labels={"path": "gather"})
  m.inc("decode_chunks_total", labels={"path": "kernel"})
  m.set_gauge("pool", 1.5, labels={"node": "a"})
  assert m.counter_value("decode_chunks_total", labels={"path": "kernel"}) == 2.0
  text = m.render_prometheus()
  assert 'xot_tpu_decode_chunks_total{path="gather"} 2.0' in text
  assert 'xot_tpu_decode_chunks_total{path="kernel"} 2.0' in text
  assert text.count("# TYPE xot_tpu_decode_chunks_total counter") == 1
  assert 'xot_tpu_pool{node="a"} 1.5' in text


def test_snapshot_merge_cluster_semantics():
  a, b = Metrics(), Metrics()
  a.inc("requests_total", 3)
  b.inc("requests_total", 4)
  a.set_gauge("scheduler_queue_depth", 2)
  b.set_gauge("scheduler_queue_depth", 5)
  a.set_gauge("page_pool_utilization", 0.9)
  b.set_gauge("page_pool_utilization", 0.4)
  a.inc("decode_chunks_total", labels={"path": "kernel"})
  b.inc("decode_chunks_total", labels={"path": "kernel"})
  for v in (0.01, 0.02):
    a.observe_hist("itl_seconds", v)
  b.observe_hist("itl_seconds", 0.04)
  a.observe_latency("req", 1.0)
  b.observe_latency("req", 3.0)
  snaps = [a.snapshot(), b.snapshot()]
  json.dumps(snaps)  # must be wire-safe (rides the opaque-status channel)
  merged = Metrics.merged(snaps)
  assert merged.counter_value("requests_total") == 7.0
  assert merged.gauges["scheduler_queue_depth"] == 7.0  # additive across nodes
  assert merged.gauges["page_pool_utilization"] == 0.9  # ratio gauges: max, not sum
  assert merged.counter_value("decode_chunks_total", labels={"path": "kernel"}) == 2.0
  assert merged.hist_count("itl_seconds") == 3
  text = merged.render_prometheus()
  assert "xot_tpu_req_seconds_count 2" in text
  assert 'xot_tpu_itl_seconds_bucket{le="+Inf"} 3' in text


def test_weighted_histogram_observation():
  """observe_hist(name, v, n=k): k identical observations in ONE lock
  acquisition — the itl_seconds path records a whole decode chunk's tokens
  this way (one call per chunk instead of a per-token Python loop)."""
  m = Metrics()
  m.observe_hist("itl_seconds", 0.02, n=5)
  m.observe_hist("itl_seconds", 0.3)  # default n=1 unchanged
  assert m.hist_count("itl_seconds") == 6
  text = m.render_prometheus()
  assert 'xot_tpu_itl_seconds_bucket{le="0.025"} 5' in text
  assert 'xot_tpu_itl_seconds_bucket{le="+Inf"} 6' in text
  assert abs(float(text.split("xot_tpu_itl_seconds_sum ")[1].split("\n")[0]) - 0.4) < 1e-9
  # Weighted quantile: 5/6 of mass in (0.01, 0.025].
  assert 0.01 < m.quantile("itl_seconds", 0.5) <= 0.025
  # n <= 0 is a no-op, not a crash (defensive for emit-empty chunks).
  m.observe_hist("itl_seconds", 1.0, n=0)
  assert m.hist_count("itl_seconds") == 6
  # Snapshot/merge round-trips weighted counts exactly.
  merged = Metrics.merged([m.snapshot(), m.snapshot()])
  assert merged.hist_count("itl_seconds") == 12


def test_labeled_histograms_render_snapshot_merge():
  """Per-peer-link RPC latency lives in LABELED histogram series
  (``peer_rpc_seconds{peer,method}``): render carries the label set next to
  ``le``, snapshot/merge round-trip per series, and label-less queries
  aggregate the family."""
  m = Metrics()
  m.observe_hist("peer_rpc_seconds", 0.02, labels={"peer": "n1", "method": "SendTensor"})
  m.observe_hist("peer_rpc_seconds", 0.02, labels={"peer": "n1", "method": "SendTensor"})
  m.observe_hist("peer_rpc_seconds", 0.3, labels={"peer": "n2", "method": "SendResult"})
  assert m.hist_count("peer_rpc_seconds", labels={"peer": "n1", "method": "SendTensor"}) == 2
  assert m.hist_count("peer_rpc_seconds") == 3  # label-less: whole family
  q = m.quantile("peer_rpc_seconds", 0.5)  # aggregate: 2/3 of mass in (0.01, 0.025]
  assert 0.01 < q <= 0.025
  assert m.quantile("peer_rpc_seconds", 0.5, labels={"peer": "n2", "method": "SendResult"}) > 0.25
  text = m.render_prometheus()
  assert text.count("# TYPE xot_tpu_peer_rpc_seconds histogram") == 1
  assert 'xot_tpu_peer_rpc_seconds_bucket{method="SendTensor",peer="n1",le="0.025"} 2' in text
  assert 'xot_tpu_peer_rpc_seconds_bucket{method="SendResult",peer="n2",le="+Inf"} 1' in text
  assert 'xot_tpu_peer_rpc_seconds_count{method="SendTensor",peer="n1"} 2' in text
  snaps = [m.snapshot(), m.snapshot()]
  json.dumps(snaps)  # wire-safe for the opaque-status channel
  merged = Metrics.merged(snaps)
  assert merged.hist_count("peer_rpc_seconds", labels={"peer": "n1", "method": "SendTensor"}) == 4
  assert merged.hist_count("peer_rpc_seconds") == 6
  # Unlabeled histograms keep their exact prior exposition shape.
  m2 = Metrics()
  m2.observe_hist("ttft_seconds", 0.02)
  assert 'xot_tpu_ttft_seconds_bucket{le="0.025"} 1' in m2.render_prometheus()


# -------------------------------------------------- decode-path attribution


def test_resolved_decode_path_matches_dispatch_table():
  from xotorch_support_jetson_tpu.inference.paging import resolved_decode_path, select_decode_path

  # Fixture points straight from the dispatch table (TPU platform).
  assert select_decode_path(16, 4096, "", platform="tpu") == "gather"
  assert select_decode_path(48, 4096, "", platform="tpu") == "dense"
  assert select_decode_path(48, 4096, "int8", platform="tpu") == "kernel"
  assert select_decode_path(8, 32768, "", platform="tpu") == "kernel"
  # Attribution: non-paged layouts are "dense"; a paged program degrades a
  # "dense" verdict to "kernel" (same rule as fused_paged_batch_decode);
  # non-TPU platforms always take the gather reference path.
  assert resolved_decode_path(16, 4096, "", paged=False, platform="tpu") == "dense"
  assert resolved_decode_path(16, 4096, "", paged=True, platform="tpu") == "gather"
  assert resolved_decode_path(48, 4096, "", paged=True, platform="tpu") == "kernel"
  assert resolved_decode_path(48, 4096, "int8", paged=True, platform="tpu") == "kernel"
  assert resolved_decode_path(48, 4096, "int8", paged=True, platform="cpu") == "gather"


# ------------------------------------------------------ scheduler telemetry


def _tiny_batched_server(n_slots=2, chunk=2):
  import jax

  from xotorch_support_jetson_tpu.inference.batch_scheduler import BatchedServer
  from xotorch_support_jetson_tpu.inference.jax_engine import JaxShardedInferenceEngine
  from xotorch_support_jetson_tpu.models.config import tiny_test_config
  from xotorch_support_jetson_tpu.models.decoder import full_model_params

  cfg = tiny_test_config(n_layers=2, max_seq_len=128)
  params, shard = full_model_params(jax.random.PRNGKey(0), cfg, "m")
  engine = JaxShardedInferenceEngine(use_local_mesh=False)
  engine.load_test_model(shard, cfg, params)
  return BatchedServer(engine, n_slots=n_slots, chunk=chunk)


def test_scheduler_gauges_counters_and_histograms(monkeypatch):
  """Admit → decode → grow → release lifecycle populates the scheduler
  telemetry: occupancy is live DURING the run, queue-wait/TTFT/ITL
  histograms fill, page grow/release counters move, and the decode-path
  chunk counter is attributed to the pool's resolved path."""
  import numpy as np

  from xotorch_support_jetson_tpu.utils.metrics import metrics as gm

  monkeypatch.setenv("XOT_TPU_PAGE_SIZE", "8")  # force page growth mid-decode
  server = _tiny_batched_server(n_slots=2, chunk=2)
  assert server.paged
  before = {
    "admit": gm.counter_value("scheduler_admissions_total"),
    "grow": gm.counter_value("page_grow_events_total"),
    "release": gm.counter_value("page_release_events_total"),
    "chunks": gm.counter_value("decode_chunks_total", labels={"path": server.decode_path}),
    "ttft": gm.hist_count("ttft_seconds"),
    "qwait": gm.hist_count("queue_wait_seconds"),
    "itl": gm.hist_count("itl_seconds"),
    "chunk_t": gm.hist_count("decode_chunk_seconds"),
    "gap": gm.hist_count("sched_host_gap_seconds"),
  }
  seen_occupancy = []

  async def run():
    def emit(rid, toks, finished):
      seen_occupancy.append(gm.gauges.get("scheduler_batch_occupancy", 0))

    await asyncio.gather(
      *(
        server.submit(f"g{i}", np.asarray([3, 25, 9 + i], np.int32), max_tokens=12, temp=0.0, top_k=35, eos_ids=(), emit=emit)
        for i in range(3)
      )
    )

  asyncio.run(run())
  assert gm.counter_value("scheduler_admissions_total") - before["admit"] == 3
  assert gm.counter_value("page_grow_events_total") > before["grow"]  # 12 tokens cross 8-token pages
  assert gm.counter_value("page_release_events_total") - before["release"] >= 3
  assert gm.counter_value("decode_chunks_total", labels={"path": server.decode_path}) > before["chunks"]
  assert gm.hist_count("ttft_seconds") - before["ttft"] == 3
  assert gm.hist_count("queue_wait_seconds") - before["qwait"] == 3
  assert gm.hist_count("itl_seconds") > before["itl"]
  assert gm.hist_count("decode_chunk_seconds") > before["chunk_t"]
  # Dispatch-boundary host gap: chained lookahead dispatches record 0 by
  # construction; sync-boundary dispatches record the real idle window.
  assert gm.hist_count("sched_host_gap_seconds") > before["gap"]
  assert max(seen_occupancy) >= 1  # rows were visibly resident mid-run
  # Idle again: gauges settle back to an empty pool.
  assert gm.gauges["scheduler_batch_occupancy"] == 0
  assert gm.gauges["scheduler_queue_depth"] == 0
  assert gm.gauges["page_pool_utilization"] == 0.0
  assert gm.gauges["page_pool_pages_total"] > 0
  server.shutdown()


def test_scheduler_rejection_counter(monkeypatch):
  import numpy as np

  from xotorch_support_jetson_tpu.inference.engine import ServerOverloadedError
  from xotorch_support_jetson_tpu.utils.metrics import metrics as gm

  server = _tiny_batched_server()
  server.max_queue = 0
  before = gm.counter_value("scheduler_rejections_total")

  async def run():
    with pytest.raises(ServerOverloadedError):
      await server.submit("rej", np.asarray([1, 2], np.int32), max_tokens=2, temp=0.0, top_k=35, eos_ids=(), emit=lambda *_: None)

  asyncio.run(run())
  assert gm.counter_value("scheduler_rejections_total") == before + 1
  server.shutdown()


# --------------------------------------------------------- tracer fixes


def test_end_request_flushes_residual_token_group():
  t = Tracer()
  t.request_context("r-res")
  for _ in range(13):  # one full group of 10 + 3 residual
    t.handle_token("r-res")
  t.end_request("r-res")
  groups = [s for s in t.recent_spans() if s["name"] == "token_group"]
  assert [g["attributes"]["n_tokens"] for g in groups] == [10, 3]
  assert groups[-1]["attributes"]["total_tokens"] == 13
  # A request ending exactly on a group boundary must NOT emit an extra span.
  t2 = Tracer()
  t2.request_context("r-even")
  for _ in range(20):
    t2.handle_token("r-even")
  t2.end_request("r-even")
  groups = [s for s in t2.recent_spans() if s["name"] == "token_group"]
  assert [g["attributes"]["n_tokens"] for g in groups] == [10, 10]


def test_trace_file_export_buffered_outside_lock(tmp_path, monkeypatch):
  """Spans still reach the JSONL file — but the hot path only queues them;
  the file write happens after the tracer lock is released."""
  path = tmp_path / "trace.jsonl"
  monkeypatch.setenv("XOT_TPU_TRACE_FILE", str(path))
  t = Tracer()  # reads the env at construction
  t.request_context("r-exp")
  with t.start_span("request.x", "r-exp"):
    pass
  for _ in range(12):
    t.handle_token("r-exp")
  t.end_request("r-exp")
  lines = [json.loads(line) for line in path.read_text().splitlines()]
  names = [entry["name"] for entry in lines]
  assert "request.x" in names
  assert names.count("token_group") == 2  # 10 + residual 2
  assert not t._export_pending  # everything flushed


# ------------------------------------------------- clock-offset estimation


def test_offset_sample_symmetric_rtt_exact():
  """With a symmetric path the NTP midpoint recovers the true offset
  exactly and rtt excludes server processing time."""
  from xotorch_support_jetson_tpu.orchestration.clocksync import offset_sample

  true_offset, one_way, proc = 1_450, 50, 100
  t0 = 1_000
  t1 = t0 + one_way + true_offset
  t2 = t1 + proc
  t3 = t2 - true_offset + one_way
  off, rtt = offset_sample(t0, t1, t2, t3)
  assert off == true_offset
  assert rtt == 2 * one_way
  # Negative offsets (peer clock BEHIND ours) come out correctly signed.
  off2, _ = offset_sample(t0, t0 + one_way - 700, t0 + one_way - 700 + proc, t0 + 2 * one_way + proc)
  assert off2 == -700


def test_clock_sync_ewma_convergence_and_uncertainty():
  from xotorch_support_jetson_tpu.orchestration.clocksync import ClockSync

  cs = ClockSync()
  true_offset, one_way = 5_000_000, 40_000  # 5 ms skew, 40 µs one-way
  # First sample seeds the estimate exactly; uncertainty = rtt/2.
  t0 = 0
  est = cs.update("peer", t0, t0 + one_way + true_offset, t0 + one_way + true_offset, t0 + 2 * one_way)
  assert est.offset_ns == true_offset
  assert est.uncertainty_ns == one_way
  # Noisy samples (±alternating asymmetry) converge around the true offset.
  for i in range(60):
    noise = 25_000 if i % 2 else -25_000
    t0 = i * 1_000_000
    t1 = t0 + one_way + noise + true_offset
    t3 = t0 + 2 * one_way
    est = cs.update("peer", t0, t1, t1, t3)
  assert abs(est.offset_ns - true_offset) < 30_000  # within the noise band
  assert est.samples == 61
  assert cs.offset_ns("peer") == est.offset_ns
  assert cs.offset_ns("never-seen") is None
  assert cs.age_s("peer") is not None and cs.age_s("peer") < 5
  cs.forget("peer")
  assert cs.estimate("peer") is None


# --------------------------------------------------------------- hop spans


def test_record_hop_spans_and_timeline_attribution():
  t = Tracer()
  ctx = t.request_context("hop-req")
  from xotorch_support_jetson_tpu.orchestration.tracing import node_now_ns

  hid = t.record_hop(
    "hop-req", side="client", method="SendTensor", peer="node-b", node="node-a",
    t_start_ns=node_now_ns(), dur_ms=1.2,
    attributes={"serialize_ms": 0.3, "rpc_ms": 0.9, "payload_bytes": 4096, "ok": True},
  )
  t.record_hop(
    "hop-req", side="server", method="SendTensor", peer="ipv4:1.2.3.4", node="node-b",
    t_start_ns=node_now_ns(), dur_ms=0.6, hop_id=hid,
    attributes={"deserialize_ms": 0.2, "handler_ms": 0.6, "payload_bytes": 4096},
  )
  spans = t.recent_spans()
  client = next(s for s in spans if s["name"] == "rpc.client.SendTensor")
  server = next(s for s in spans if s["name"] == "rpc.server.SendTensor")
  assert client["span_id"] == hid and client["trace_id"] == ctx.trace_id
  assert server["parent_id"] == hid  # server hop parents to the client hop
  assert client["attributes"]["serialize_ms"] == 0.3 and client["attributes"]["payload_bytes"] == 4096
  assert server["attributes"]["handler_ms"] == 0.6
  tl = t.timeline("hop-req")
  assert [h["side"] for h in tl["hops"]] == ["client", "server"]
  assert tl["hops"][0]["hop_id"] == hid and tl["hops"][1]["hop_id"] == hid
  # Exact per-link aggregates ride alongside the capped detail.
  agg = tl["hop_agg"]["client|node-a|node-b|SendTensor"]
  assert agg["count"] == 1 and agg["rpc_ms_sum"] == 0.9 and agg["payload_bytes_sum"] == 4096


def test_hop_detail_capped_aggregates_exact():
  from xotorch_support_jetson_tpu.orchestration import tracing

  t = Tracer()
  t.request_context("hop-cap")
  n = tracing.MAX_TIMELINE_HOPS + 20
  for _ in range(n):
    t.record_hop(
      "hop-cap", side="client", method="SendResult", peer="p", node="n",
      t_start_ns=tracing.node_now_ns(), dur_ms=0.1, attributes={"rpc_ms": 0.1},
    )
  tl = t.timeline("hop-cap")
  assert len(tl["hops"]) == tracing.MAX_TIMELINE_HOPS
  assert tl["hops_dropped"] == 20
  assert tl["hop_agg"]["client|n|p|SendResult"]["count"] == n  # exact past the cap
  # The span RING rides the same cap: per-token hop spans must not cycle the
  # whole ring and bury request/pp/token-group spans.
  ring = [s for s in t.recent_spans(n + 50) if s["name"] == "rpc.client.SendResult"]
  assert len(ring) == tracing.MAX_TIMELINE_HOPS


def test_merge_cluster_timeline_offset_normalization():
  """Known injected skew: node B's clock runs 7 ms ahead. The merge must
  subtract the estimated offset so B's events/hops land where they really
  happened in A's clock domain — correctly signed, monotonic order."""
  from xotorch_support_jetson_tpu.orchestration.tracing import (
    merge_cluster_timeline, node_now_ns, set_test_skew,
  )

  set_test_skew("B", 7_000_000)
  try:
    t = Tracer()
    t.request_context("merge-req")
    t.stage("merge-req", "queued", node="A")
    hid = t.record_hop(
      "merge-req", side="client", method="SendTensor", peer="B", node="A",
      t_start_ns=node_now_ns("A"), dur_ms=1.0,
      attributes={"serialize_ms": 0.3, "rpc_ms": 0.7, "payload_bytes": 128},
    )
    t.record_hop(
      "merge-req", side="server", method="SendTensor", peer="ipv4:x", node="B",
      t_start_ns=node_now_ns("B"), dur_ms=0.5, hop_id=hid,
      attributes={"deserialize_ms": 0.1, "handler_ms": 0.5, "payload_bytes": 128},
    )
    t.stage("merge-req", "decode", node="B")
    t.end_request("merge-req")
    exp = t.timeline_export("merge-req")

    # WITHOUT the offset, B's entries sit ~7 ms in the future.
    raw = merge_cluster_timeline("A", exp, [{"node_id": "B", "fragment": exp}], {})
    raw_hop = raw["hops"][0]
    assert raw_hop["recv_at_ms"] - raw_hop["at_ms"] > 5.0

    # WITH the (exactly-known) offset the order is restored: send < recv
    # within sub-ms slop, and B's decode follows A's queued by wall time.
    merged = merge_cluster_timeline("A", exp, [{"node_id": "B", "fragment": exp}], {"B": {"offset_ns": 7_000_000}})
    assert merged["nodes"] == ["A", "B"]
    hop = merged["hops"][0]
    assert hop["from"] == "A" and hop["to"] == "B" and hop["method"] == "SendTensor"
    # Hop attribution splits: serialize / wire / deserialize / compute.
    assert hop["serialize_ms"] == 0.3
    assert hop["deserialize_ms"] == 0.1
    assert hop["wire_ms"] == pytest.approx(0.7 - 0.5)
    assert hop["compute_ms"] == pytest.approx(0.5 - 0.1)
    assert abs(hop["recv_at_ms"] - hop["at_ms"]) < 2.0  # the 7 ms skew is gone
    order = [(e["node"], e["stage"]) for e in merged["events"]]
    assert order == [("A", "queued"), ("B", "decode")]
    # Shared-tracer fragments (both "nodes" exported the same object) do
    # not duplicate events, hops, or aggregate sums.
    assert len(merged["events"]) == 2 and len(merged["hops"]) == 1
    assert merged["hop_agg"]["client|A|B|SendTensor"]["count"] == 1
    # Per-node stage rollups are present for both nodes.
    assert set(merged["stages"]) == {"A", "B"}
    # t=0 is the earliest normalized event anywhere; nothing goes negative.
    assert merged["events"][0]["at_ms"] == 0.0
    assert all(e["at_ms"] >= 0 for e in merged["events"])
    # Off-origin merge (no local fragment — e.g. the query landed on a node
    # that only saw the tail of the request): same guarantee.
    remote_only = merge_cluster_timeline("C", None, [{"node_id": "B", "fragment": exp}], {"B": {"offset_ns": 7_000_000}})
    assert min(e["at_ms"] for e in remote_only["events"]) == 0.0
    assert remote_only["total_ms"] >= 0
  finally:
    set_test_skew("B", None)


# ----------------------------------------------------------- timelines


def test_stage_timeline_shape_and_rollup():
  t = Tracer()
  t.request_context("r-tl")
  t.stage("r-tl", "queued")
  t.stage("r-tl", "admitted", {"row": 1})
  t.stage("r-tl", "prefill_chunk", {"tokens": 2048})
  t.stage("r-tl", "prefill_chunk", {"tokens": 512})
  t.stage("r-tl", "decode")
  for _ in range(5):
    t.handle_token("r-tl")
  t.end_request("r-tl")
  t.stage("r-tl", "detokenize")  # API-side, lands after the finish
  tl = t.timeline("r-tl")
  assert tl["finished"] and tl["tokens"] == 5
  assert [s["stage"] for s in tl["stages"]] == ["queued", "admitted", "prefill_chunk", "decode", "detokenize"]
  chunks = next(s for s in tl["stages"] if s["stage"] == "prefill_chunk")
  assert chunks["count"] == 2
  assert tl["total_ms"] >= 0
  assert [e["attributes"].get("tokens") for e in tl["events"] if e["stage"] == "prefill_chunk"] == [2048, 512]
  assert all(e["at_ms"] >= 0 for e in tl["events"])
  assert t.timeline("never-seen") is None


def test_timeline_lru_bounded():
  from xotorch_support_jetson_tpu.orchestration import tracing

  t = Tracer()
  for i in range(tracing.MAX_TIMELINES + 10):
    t.stage(f"r{i}", "queued")
  assert len(t.timelines) == tracing.MAX_TIMELINES
  assert t.timeline("r0") is None  # oldest evicted
  assert t.timeline(f"r{tracing.MAX_TIMELINES + 9}") is not None


def test_slow_request_log(monkeypatch, capsys):
  monkeypatch.setenv("XOT_TPU_SLOW_REQUEST_MS", "0.000001")
  t = Tracer()
  t.request_context("r-slow")
  t.stage("r-slow", "queued")
  t.stage("r-slow", "decode")
  t.handle_token("r-slow")
  t.end_request("r-slow")
  out = capsys.readouterr().out
  line = next(json.loads(entry) for entry in out.splitlines() if '"slow_request"' in entry)
  assert line["event"] == "slow_request" and line["request_id"] == "r-slow"
  assert [s["stage"] for s in line["stages"]] == ["queued", "decode"]
  assert line["tokens"] == 1
  # Below threshold: silent.
  monkeypatch.setenv("XOT_TPU_SLOW_REQUEST_MS", "1e9")
  t.request_context("r-fast")
  t.stage("r-fast", "queued")
  t.end_request("r-fast")
  assert "slow_request" not in capsys.readouterr().out


# ------------------------------------------------------- metric-name snapshot

# The serving stack's exposition contract: every name the instrumentation
# emits, frozen so dashboards/alerts don't silently break. Adding a metric
# means adding it HERE (and to the README table); renaming one is a breaking
# change and should be called out in CHANGES.md.
EXPECTED_METRIC_NAMES = {
  # counters
  "xot_tpu_requests_total",
  "xot_tpu_requests_replayed_total",
  "xot_tpu_tokens_generated_total",
  "xot_tpu_scheduler_submitted_total",
  "xot_tpu_scheduler_admissions_total",
  "xot_tpu_scheduler_rejections_total",
  "xot_tpu_scheduler_parked_total",
  "xot_tpu_scheduler_admission_failures_total",
  "xot_tpu_scheduler_preemptions_total",
  "xot_tpu_scheduler_page_starved_total",
  "xot_tpu_decode_chunks_total",
  "xot_tpu_decode_tokens_total",
  "xot_tpu_prefill_chunks_total",
  "xot_tpu_prefix_cache_hit_pages_total",
  "xot_tpu_page_grow_events_total",
  "xot_tpu_page_grow_pages_total",
  "xot_tpu_page_release_events_total",
  "xot_tpu_grpc_rpcs_total",
  "xot_tpu_grpc_rpc_failures_total",
  # QoS subsystem (ISSUE 5; labeled {class} / {tenant} / {reason})
  "xot_tpu_qos_submitted_total",
  "xot_tpu_qos_shed_total",
  "xot_tpu_qos_rejected_total",
  "xot_tpu_qos_rate_limited_total",
  "xot_tpu_qos_preemptions_total",
  # Batched speculation (ISSUE 7; spec_gamma labeled {row}; since ISSUE 12
  # the token counters are labeled {proposer} and spec_proposer{row} reports
  # each row's active proposer: 0 plain / 1 n-gram / 2 model draft)
  "xot_tpu_spec_proposed_tokens_total",
  "xot_tpu_spec_accepted_tokens_total",
  "xot_tpu_spec_proposer",
  # KV memory hierarchy (ISSUE 6; registry hits labeled {scope})
  "xot_tpu_kv_tier_spilled_pages_total",
  "xot_tpu_kv_tier_spilled_bytes_total",
  "xot_tpu_kv_tier_restored_pages_total",
  "xot_tpu_kv_tier_restored_bytes_total",
  "xot_tpu_kv_tier_host_evictions_total",
  "xot_tpu_kv_prefix_registry_hits_total",
  "xot_tpu_peer_broadcast_failures_total",
  "xot_tpu_peer_rpc_bytes_sent_total",
  "xot_tpu_peer_rpc_bytes_received_total",
  "xot_tpu_peer_rpc_failures_total",
  # Fault tolerance (ISSUE 8; retries labeled {method})
  "xot_tpu_rpc_retries_total",
  "xot_tpu_drain_migrations_total",
  "xot_tpu_requests_recovered_total",
  "xot_tpu_requests_stalled_total",
  # Mixed prefill+decode ticks (ISSUE 14)
  "xot_tpu_sched_tick_prefill_tokens_total",
  # Disaggregated prefill/decode (ISSUE 10)
  "xot_tpu_kv_stream_pages_total",
  "xot_tpu_kv_stream_bytes_total",
  "xot_tpu_kv_stream_adopted_pages_total",
  "xot_tpu_disagg_handoffs_total",
  # Cluster front door (ISSUE 13; requests labeled {target}, hits {source},
  # throttles {tenant})
  "xot_tpu_router_requests_total",
  "xot_tpu_router_prefix_hits_total",
  "xot_tpu_router_failovers_total",
  "xot_tpu_router_tenant_throttled_total",
  # SLO engine + flight recorder (ISSUE 9)
  "xot_tpu_slo_requests_good_total",  # {class}
  "xot_tpu_slo_requests_bad_total",  # {class,reason}
  "xot_tpu_slo_tokens_total",  # {class,tenant}
  "xot_tpu_slo_good_tokens_total",  # {class,tenant}
  "xot_tpu_flightrec_events_total",  # {type}
  "xot_tpu_anomalies_total",  # {rule}
  "xot_tpu_incident_bundles_total",  # {trigger}
  # Device-program ledger (ISSUE 19; all labeled {family})
  "xot_tpu_program_compiles_total",
  "xot_tpu_program_steady_compiles_total",
  "xot_tpu_program_dispatch_total",
  # gauges
  "xot_tpu_scheduler_batch_occupancy",
  "xot_tpu_scheduler_queue_depth",
  "xot_tpu_scheduler_parked",
  "xot_tpu_scheduler_prefilling",
  "xot_tpu_scheduler_slots_total",
  "xot_tpu_page_pool_pages_total",
  "xot_tpu_page_pool_pages_free",
  "xot_tpu_page_pool_pages_cached",
  "xot_tpu_page_pool_utilization",
  "xot_tpu_qos_queue_depth",
  "xot_tpu_spec_gamma",
  "xot_tpu_kv_draft_bytes",
  "xot_tpu_kv_draft_slots",
  "xot_tpu_kv_draft_pages_equivalent",
  "xot_tpu_kv_tier_host_pages",
  "xot_tpu_kv_tier_host_bytes",
  "xot_tpu_kv_tier_host_utilization",
  "xot_tpu_engine_sessions",
  "xot_tpu_peer_clock_offset_ms",
  "xot_tpu_peer_clock_uncertainty_ms",
  "xot_tpu_peer_circuit_state",
  "xot_tpu_cluster_nodes_reporting",
  "xot_tpu_slo_burn_rate",  # {class,window}
  "xot_tpu_slo_attainment",  # {class}
  "xot_tpu_goodput_tok_s",  # {class}
  "xot_tpu_node_role",  # 0=both 1=prefill 2=decode (ISSUE 10)
  "xot_tpu_paged_kernel_tile",  # shape-aware page-tile verdict for this pool (ISSUE 11)
  "xot_tpu_kv_quant_bits",  # 16=bf16 8=int8 4=int4 (ISSUE 11)
  "xot_tpu_mixed_budget_tokens",  # the tick planner's current prefill-slice budget (ISSUE 14)
  # Multi-LoRA serving (ISSUE 15; swaps labeled {direction}, requests
  # labeled {adapter} — adapter names are client-asserted, same trust note
  # as tenant keys)
  "xot_tpu_lora_adapters_resident",
  "xot_tpu_lora_host_bytes",
  "xot_tpu_lora_swaps_total",
  "xot_tpu_lora_requests_total",
  "xot_tpu_lora_swap_seconds",
  # Device-program ledger (ISSUE 19)
  "xot_tpu_programs_steady",  # 0 warming / 1 steady (post-warmup sentinel armed)
  "xot_tpu_warmup_programs",  # manifest size of the last warmup
  # histograms
  "xot_tpu_ttft_seconds",
  "xot_tpu_itl_seconds",
  "xot_tpu_qos_ttft_seconds",  # {class} (ISSUE 9 — the SLO engine's windows)
  "xot_tpu_qos_itl_seconds",  # {class}
  "xot_tpu_queue_wait_seconds",
  "xot_tpu_prefill_chunk_seconds",
  "xot_tpu_decode_chunk_seconds",
  "xot_tpu_mixed_tick_seconds",  # one fused mixed prefill+decode dispatch (ISSUE 14)
  "xot_tpu_sched_host_gap_seconds",
  "xot_tpu_spec_acceptance_ewma",
  "xot_tpu_kv_tier_spill_seconds",
  "xot_tpu_kv_tier_restore_seconds",
  "xot_tpu_kv_tier_restore_pages_per_op",
  "xot_tpu_kv_stream_seconds",  # {peer} (ISSUE 10 — disagg KV-page transfer)
  "xot_tpu_prefill_seconds",
  "xot_tpu_decode_step_seconds",
  # Device-program ledger (ISSUE 19; compile/device labeled {family})
  "xot_tpu_program_compile_seconds",
  "xot_tpu_program_device_seconds",
  "xot_tpu_warmup_compile_seconds",
  # per-peer-link RPC attribution (ISSUE 4; labeled {peer,method} / {method})
  "xot_tpu_peer_rpc_seconds",
  "xot_tpu_peer_rpc_serialize_seconds",
  "xot_tpu_grpc_handler_seconds",
  "xot_tpu_grpc_deserialize_seconds",
}


def test_metric_name_snapshot_after_serving():
  """Drive the batched scheduler once, then assert the exposition carries
  every frozen metric name (and only well-formed xot_tpu_* families)."""
  import re

  import numpy as np

  from xotorch_support_jetson_tpu.utils.metrics import metrics as gm

  server = _tiny_batched_server()

  async def run():
    await server.submit("snap", np.asarray([5, 6, 7], np.int32), max_tokens=4, temp=0.0, top_k=35, eos_ids=(), emit=lambda *_: None)

  asyncio.run(run())
  server.shutdown()
  # Families emitted by paths this scheduler-only drive doesn't hit (node
  # ring/replay, gRPC plane, rarely-taken scheduler branches): materialize
  # them at zero so the pin covers the WHOLE documented exposition contract.
  for name in (
    "requests_total", "requests_replayed_total", "tokens_generated_total",
    "scheduler_rejections_total", "scheduler_parked_total",
    "scheduler_admission_failures_total", "scheduler_preemptions_total",
    "scheduler_page_starved_total", "prefix_cache_hit_pages_total",
    "kv_tier_spilled_pages_total", "kv_tier_spilled_bytes_total",
    "kv_tier_restored_pages_total", "kv_tier_restored_bytes_total",
    "kv_tier_host_evictions_total",
    # Event-driven pool counters: a short solo drive may finish inside its
    # initial allocation and never grow (module-order dependent — earlier
    # test modules usually materialize these into the process-global
    # registry, but the pin must hold in isolation too).
    "page_grow_events_total", "page_grow_pages_total", "page_release_events_total",
  ):
    gm.inc(name, 0)
  gm.inc("kv_prefix_registry_hits_total", 0, labels={"scope": "local"})
  gm.inc("spec_proposed_tokens_total", 0, labels={"proposer": "ngram"})
  gm.inc("spec_accepted_tokens_total", 0, labels={"proposer": "ngram"})
  gm.set_gauge("spec_gamma", 0, labels={"row": "0"})
  gm.set_gauge("spec_proposer", 0, labels={"row": "0"})
  gm.set_gauge("kv_draft_bytes", 0)
  gm.set_gauge("kv_draft_slots", 0)
  gm.set_gauge("kv_draft_pages_equivalent", 0)
  # Mixed ticks (ISSUE 14): a short solo drive never stages a chunked
  # prefill next to resident decode rows, so the mixed families stay
  # event-driven — materialize them at zero for the exposition pin.
  gm.inc("sched_tick_prefill_tokens_total", 0)
  gm.observe_hist("mixed_tick_seconds", 0.0)
  gm.set_gauge("mixed_budget_tokens", 0)
  # Multi-LoRA (ISSUE 15): registry families are event-driven (a solo
  # drive loads no adapter) — materialize them at zero for the pin.
  gm.set_gauge("lora_adapters_resident", 0)
  gm.set_gauge("lora_host_bytes", 0)
  gm.inc("lora_swaps_total", 0, labels={"direction": "in"})
  gm.inc("lora_requests_total", 0, labels={"adapter": "base"})
  gm.observe_hist("lora_swap_seconds", 0.0)
  from xotorch_support_jetson_tpu.utils.metrics import FRACTION_BUCKETS

  gm.observe_hist("spec_acceptance_ewma", 0.0, buckets=FRACTION_BUCKETS)
  gm.set_gauge("kv_tier_host_pages", 0)
  gm.set_gauge("kv_tier_host_bytes", 0)
  gm.set_gauge("kv_tier_host_utilization", 0.0)
  gm.observe_hist("kv_tier_spill_seconds", 0.0)
  gm.observe_hist("kv_tier_restore_seconds", 0.0)
  from xotorch_support_jetson_tpu.utils.metrics import SIZE_BUCKETS

  gm.observe_hist("kv_tier_restore_pages_per_op", 0, buckets=SIZE_BUCKETS)
  gm.inc("grpc_rpcs_total", 0, labels={"method": "SendResult"})
  gm.inc("grpc_rpc_failures_total", 0, labels={"method": "SendResult"})
  gm.inc("qos_submitted_total", 0, labels={"class": "standard"})
  gm.inc("qos_shed_total", 0, labels={"reason": "deadline"})
  gm.inc("qos_rejected_total", 0, labels={"class": "batch"})
  gm.inc("qos_rate_limited_total", 0, labels={"tenant": "default"})
  gm.inc("qos_preemptions_total", 0)
  gm.set_gauge("qos_queue_depth", 0, labels={"class": "standard"})
  gm.inc("peer_broadcast_failures_total", 0, labels={"kind": "result"})
  gm.observe_hist("prefill_seconds", 0.0)
  gm.observe_hist("decode_step_seconds", 0.0)
  gm.set_gauge("engine_sessions", 0)
  link = {"peer": "peer-0", "method": "SendTensor"}
  gm.inc("peer_rpc_bytes_sent_total", 0, labels=link)
  gm.inc("peer_rpc_bytes_received_total", 0, labels=link)
  gm.inc("peer_rpc_failures_total", 0, labels=link)
  gm.observe_hist("peer_rpc_seconds", 0.0, labels=link)
  gm.observe_hist("peer_rpc_serialize_seconds", 0.0, labels={"method": "SendTensor"})
  gm.observe_hist("grpc_handler_seconds", 0.0, labels={"method": "SendTensor"})
  gm.observe_hist("grpc_deserialize_seconds", 0.0, labels={"method": "SendTensor"})
  gm.set_gauge("peer_clock_offset_ms", 0.0, labels={"peer": "peer-0"})
  gm.set_gauge("peer_clock_uncertainty_ms", 0.0, labels={"peer": "peer-0"})
  gm.inc("rpc_retries_total", 0, labels={"method": "SendResult"})
  gm.inc("drain_migrations_total", 0)
  gm.inc("requests_recovered_total", 0)
  gm.inc("requests_stalled_total", 0)
  gm.set_gauge("peer_circuit_state", 0, labels={"peer": "peer-0"})
  # SLO engine + flight recorder (ISSUE 9): families emitted by the SLO
  # accounting hooks / tick and the recorder — materialized at zero when the
  # drive above ran with the engines quiet.
  gm.inc("slo_requests_good_total", 0, labels={"class": "standard"})
  gm.inc("slo_requests_bad_total", 0, labels={"class": "standard", "reason": "shed"})
  gm.inc("slo_tokens_total", 0, labels={"class": "standard", "tenant": "default"})
  gm.inc("slo_good_tokens_total", 0, labels={"class": "standard", "tenant": "default"})
  gm.inc("flightrec_events_total", 0, labels={"type": "admitted"})
  gm.inc("anomalies_total", 0, labels={"rule": "burn_rate"})
  gm.inc("incident_bundles_total", 0, labels={"trigger": "stall"})
  gm.set_gauge("cluster_nodes_reporting", 1)
  # Disaggregated prefill/decode (ISSUE 10): emitted by the node's KV
  # stream / handoff path and the decode-side adopt — off in this drive.
  gm.inc("kv_stream_pages_total", 0)
  gm.inc("kv_stream_bytes_total", 0)
  gm.inc("kv_stream_adopted_pages_total", 0)
  gm.inc("disagg_handoffs_total", 0)
  # Cluster front door (ISSUE 13): emitted only by a router-mode API.
  gm.inc("router_requests_total", 0, labels={"target": "replica-0"})
  gm.inc("router_prefix_hits_total", 0, labels={"source": "advert"})
  gm.inc("router_failovers_total", 0)
  gm.inc("router_tenant_throttled_total", 0, labels={"tenant": "default"})
  gm.observe_hist("kv_stream_seconds", 0.0, labels={"peer": "peer-0"})
  gm.set_gauge("node_role", 0)
  # Device-program ledger (ISSUE 19): the drive itself compiles and
  # dispatches tracked programs (program_compiles_total / dispatch /
  # compile+device seconds land naturally); the STEADY families are
  # event-driven — no warmup ran, nothing recompiled post-steady.
  gm.inc("program_steady_compiles_total", 0, labels={"family": "decode.batch"})
  gm.set_gauge("programs_steady", 0)
  gm.set_gauge("warmup_programs", 0)
  gm.observe_hist("warmup_compile_seconds", 0.0)
  gm.set_gauge("slo_burn_rate", 0.0, labels={"class": "standard", "window": "300s"})
  gm.set_gauge("slo_attainment", 1.0, labels={"class": "standard"})
  gm.set_gauge("goodput_tok_s", 0.0, labels={"class": "standard"})
  gm.observe_hist("qos_ttft_seconds", 0.0, labels={"class": "standard"})
  gm.observe_hist("qos_itl_seconds", 0.0, labels={"class": "standard"})
  text = gm.render_prometheus()
  families = set(re.findall(r"# TYPE (xot_tpu_[a-z0-9_]+) \w+", text))
  missing = EXPECTED_METRIC_NAMES - families
  assert not missing, f"exposition lost metric families: {sorted(missing)}"
  assert all(re.fullmatch(r"xot_tpu_[a-z0-9_]+", f) for f in families)


# ------------------------------------------------- cluster-wide aggregation


@pytest.mark.asyncio
async def test_cluster_metrics_pull_over_opaque_status():
  """Two nodes bridged by in-process 'peers': the API node's pull broadcast
  reaches the peer, the peer replies with its snapshot over the same opaque
  channel, and the merged exposition carries both registries."""
  from xotorch_support_jetson_tpu.inference.dummy_engine import DummyInferenceEngine
  from xotorch_support_jetson_tpu.orchestration.node import Node
  from xotorch_support_jetson_tpu.topology.partitioning import RingMemoryWeightedPartitioningStrategy
  from xotorch_support_jetson_tpu.utils.metrics import Metrics, metrics as gm
  from tests_support_stubs import NoDiscovery, StubServer

  def make_node(name):
    return Node(name, StubServer(), DummyInferenceEngine(), NoDiscovery(), None, RingMemoryWeightedPartitioningStrategy())

  a, b = make_node("agg-a"), make_node("agg-b")

  class BridgePeer:
    def __init__(self, me, other):
      self._me, self._other = me, other

    def id(self):
      return self._other.id

    async def send_opaque_status(self, request_id, status):
      self._other.on_opaque_status.trigger_all(request_id, status)
      await asyncio.sleep(0)  # let the receiver's created tasks run

  a.peers = [BridgePeer(a, b)]
  b.peers = [BridgePeer(b, a)]

  gm.inc("requests_total", 0)  # ensure the family exists locally
  snaps = await a.collect_cluster_metrics(timeout=2.0)
  assert len(snaps) == 1
  merged = Metrics.merged([gm.snapshot(), *snaps])
  text = merged.render_prometheus()
  assert "xot_tpu_requests_total" in text

  # No peers → instant empty pull (the API then renders local-only).
  a.peers = []
  assert await a.collect_cluster_metrics(timeout=0.1) == []


# ------------------------------------------------------------ API endpoints


async def _dummy_api():
  from xotorch_support_jetson_tpu.api.chatgpt_api import ChatGPTAPI
  from xotorch_support_jetson_tpu.inference.dummy_engine import DummyInferenceEngine
  from xotorch_support_jetson_tpu.orchestration.node import Node
  from xotorch_support_jetson_tpu.topology.partitioning import RingMemoryWeightedPartitioningStrategy
  from aiohttp.test_utils import TestClient, TestServer
  from tests_support_stubs import NoDiscovery, StubServer

  node = Node(
    "obs-api-node", StubServer(), DummyInferenceEngine(), NoDiscovery(), None,
    RingMemoryWeightedPartitioningStrategy(), max_generate_tokens=16,
  )
  await node.start()
  api = ChatGPTAPI(node, "DummyInferenceEngine", response_timeout=30, default_model="dummy")
  client = TestClient(TestServer(api.app))
  await client.start_server()
  return node, api, client


@pytest.mark.asyncio
async def test_timeline_endpoint_and_metrics_scope():
  node, api, client = await _dummy_api()
  try:
    resp = await client.post(
      "/v1/chat/completions",
      json={"model": "dummy", "messages": [{"role": "user", "content": "aaaa"}], "stream": False},
    )
    assert resp.status == 200, await resp.text()
    data = await resp.json()
    request_id = data["id"].removeprefix("chatcmpl-")

    resp = await client.get(f"/v1/requests/{request_id}/timeline")
    assert resp.status == 200, await resp.text()
    tl = await resp.json()
    assert tl["request_id"] == request_id and tl["finished"]
    stages = [s["stage"] for s in tl["stages"]]
    for expected in ("queued", "admitted", "prefill_chunk", "decode", "detokenize"):
      assert expected in stages, (expected, stages)
    assert tl["total_ms"] > 0 and tl["tokens"] > 0
    assert {"stage", "count", "first_at_ms", "duration_ms"} <= set(tl["stages"][0])

    resp = await client.get("/v1/requests/not-a-request/timeline")
    assert resp.status == 404

    # /metrics local and cluster scopes both render; cluster adds the
    # reporting-node gauge even with zero peers.
    resp = await client.get("/metrics")
    assert resp.status == 200
    local_text = await resp.text()
    assert "xot_tpu_requests_total" in local_text
    resp = await client.get("/metrics?scope=cluster")
    assert resp.status == 200
    cluster_text = await resp.text()
    assert "xot_tpu_cluster_nodes_reporting 1" in cluster_text
    assert "xot_tpu_requests_total" in cluster_text
  finally:
    await client.close()
    await node.stop()


@pytest.mark.asyncio
async def test_traces_endpoint_query_hardening():
  """GET /v1/traces (ISSUE 4 satellite): non-integer n → 400 (used to crash
  the handler into a 500); huge n clamps to the ring-buffer capacity."""
  node, api, client = await _dummy_api()
  try:
    resp = await client.get("/v1/traces")
    assert resp.status == 200
    assert "spans" in await resp.json()

    for bad in ("abc", "1.5", ""):
      resp = await client.get("/v1/traces", params={"n": bad})
      assert resp.status == 400, (bad, await resp.text())

    resp = await client.get("/v1/traces", params={"n": "-3"})
    assert resp.status == 400

    from xotorch_support_jetson_tpu.orchestration.tracing import tracer

    resp = await client.get("/v1/traces", params={"n": str(10**9)})
    assert resp.status == 200
    spans = (await resp.json())["spans"]
    assert len(spans) <= tracer.spans.maxlen
  finally:
    await client.close()
    await node.stop()


@pytest.mark.asyncio
async def test_profile_endpoint(tmp_path, monkeypatch):
  node, api, client = await _dummy_api()
  try:
    monkeypatch.setenv("XOT_TPU_PROFILE", "0")
    resp = await client.post("/v1/profile", json={})
    assert resp.status == 403
    monkeypatch.delenv("XOT_TPU_PROFILE")

    resp = await client.post("/v1/profile", json={"duration_ms": -5})
    assert resp.status == 400

    out_dir = str(tmp_path / "prof")
    resp = await client.post("/v1/profile", json={"duration_ms": 50, "dir": out_dir})
    # 200 when jax.profiler works here; 503 is the documented no-op when the
    # backend can't trace — either way the endpoint must not 500.
    assert resp.status in (200, 503), await resp.text()
    if resp.status == 200:
      data = await resp.json()
      assert data["dir"] == out_dir
      assert data["duration_ms"] >= 50
      import os

      assert os.path.isdir(out_dir)
  finally:
    await client.close()
    await node.stop()
