"""Draft-free speculation: prompt-lookup / n-gram proposers in the batched
spec path, selected per row by the acceptance-EWMA policy (ISSUE 12,
inference/ngram.py + the proposer hooks in batch_scheduler.py / decoder.py /
jax_engine.py).

The correctness contract is PR 7's, extended: greedy batched output with the
n-gram proposer is TOKEN-IDENTICAL to plain batched decode (itself pinned
against solo greedy) on every layout (paged-int8KV, paged-int4KV, dense),
lookahead on or off, for ANY proposal content — adversarial streams reject
cleanly with no position drift. Draft-free speculation holds no device
state: the kv_draft_* gauges read 0 and the page budget is untouched. The
per-row policy converges: a row whose text never pays falls back to plain
(the spec dispatches STOP), a repetitive row stays on n-gram at full depth,
and with a dead draft model loaded the policy switches rows model → n-gram.

(The suite-wide conftest pins XOT_TPU_SPEC_NGRAM=0 so the rest of tier-1
keeps its plain-program compile budget; every test here opts in.)
"""

import asyncio

import jax
import numpy as np
import pytest

from tests.test_batched import _single_row_reference
from tests.test_lookahead import _serve
from xotorch_support_jetson_tpu.inference.batch_scheduler import BatchedServer
from xotorch_support_jetson_tpu.inference.jax_engine import JaxShardedInferenceEngine
from xotorch_support_jetson_tpu.inference.ngram import NgramIndex
from xotorch_support_jetson_tpu.models.config import tiny_test_config
from xotorch_support_jetson_tpu.models.decoder import full_model_params
from xotorch_support_jetson_tpu.models.quantize import quantize_params
from xotorch_support_jetson_tpu.utils.metrics import metrics as gm
from xotorch_support_jetson_tpu.utils.synthetic import peaked_echo_params

CFG = tiny_test_config(n_layers=2, max_seq_len=256, tied_embedding=True)
KEY = jax.random.PRNGKey(0)
# Repetition-heavy prompts (the RAG/code-edit/multi-turn shape): the echo
# model continues the periodic stream, so suffix matches both FIRE and ACCEPT.
PROMPTS = [[3, 25, 9, 7] * 3, [7, 1, 88, 42, 5, 7, 1, 88, 42, 5], [9, 9, 9, 1, 9, 9, 9, 1], [100, 4, 100, 4, 100]]


def _engine(cfg=CFG, key=KEY, echo=True, spec_decode=None):
  """Draft-free engine (no XOT_TPU_SPEC_DECODE draft pair): the only
  speculation available is the n-gram proposer."""
  params, shard = full_model_params(key, cfg, "m")
  if echo:
    params = peaked_echo_params(params)
  engine = JaxShardedInferenceEngine(use_local_mesh=False, spec_decode=spec_decode)
  engine.load_test_model(shard, cfg, params)
  assert engine._draft_params is None
  return engine, params, shard


def _ngram_ab(engine, params, shard, prompts, n_gen, *, chunk=4, n_slots=4, cfg=CFG):
  """Spec×lookahead A/B (the test_spec_batch harness shape): all four modes
  token-identical to solo greedy, with the spec servers resolving DRAFT-FREE
  n-gram speculation."""
  expected = [_single_row_reference(params, shard, p, n_gen - 1, cfg=cfg) for p in prompts]
  for spec in (True, False):
    for la in (True, False):
      server = BatchedServer(engine, n_slots=n_slots, chunk=chunk, lookahead=la, spec_batch=spec)
      outs, streams = _serve(server, prompts, n_gen)
      for o, s in zip(outs, streams):
        assert s == o
      if spec:
        assert server.spec and server.spec_proposers == ("ngram",)
        assert server.draft_cache is None
      assert outs == expected, f"(spec={spec}, la={la}) diverged: {outs} != {expected}"
      server.shutdown()
  return expected


# ------------------------------------------------------------- unit layer


def test_ngram_index_longest_match_wins_and_previous_occurrence():
  idx = NgramIndex(n=3)
  idx.extend([1, 2, 3, 9, 1, 2, 3])
  # Suffix [1,2,3] matched at its PREVIOUS occurrence (ending pos 2): the
  # continuation there was 9, 1, 2...
  assert idx.propose(3).tolist() == [9, 1, 2]
  # Longest match wins over shorter suffixes: after appending 9 the suffix
  # [2,3,9] occurred before (ending pos 3) — continuation 1,2,3.
  idx.extend([9])
  assert idx.propose(4).tolist() == [1, 2, 3, 9]
  # No earlier occurrence at any length: miss.
  fresh = NgramIndex(n=3)
  fresh.extend([5, 6, 7])
  assert fresh.propose(4).size == 0
  # 1-gram fallback: only the last token repeats.
  uni = NgramIndex(n=3)
  uni.extend([4, 8, 4])
  assert uni.propose(2).tolist() == [8, 4]
  # Empty history / zero budget.
  assert NgramIndex(n=2).propose(4).size == 0
  assert idx.propose(0).size == 0


def test_ngram_knobs(monkeypatch):
  from xotorch_support_jetson_tpu.inference.ngram import ngram_enabled, ngram_knobs

  monkeypatch.setenv("XOT_TPU_SPEC_NGRAM", "1")
  monkeypatch.setenv("XOT_TPU_SPEC_NGRAM_N", "2")
  monkeypatch.setenv("XOT_TPU_SPEC_NGRAM_MAX", "5")
  assert ngram_enabled() and ngram_knobs() == (2, 5)
  idx = NgramIndex()  # knob-driven suffix length
  assert idx.n == 2
  monkeypatch.setenv("XOT_TPU_SPEC_NGRAM", "0")
  assert not ngram_enabled()


def test_proposer_selection_policy():
  """spec_select_proposer / spec_reprobe_proposer (inference/paging.py):
  untried alternatives probe at depth 1, measured-dead ones don't bounce,
  plain is the floor, and re-probes rank unmeasured > best-EWMA."""
  from xotorch_support_jetson_tpu.inference.paging import spec_reprobe_proposer, spec_select_proposer

  both = ("model", "ngram")
  # Model collapsed, n-gram untried: probe it.
  assert spec_select_proposer("model", {"model": 0.1}, both) == ("ngram", 1)
  # Both measured dead: plain (no proposer ping-pong).
  assert spec_select_proposer("model", {"model": 0.1, "ngram": 0.05}, both) == ("plain", 0)
  # The alternative still clears the demote bar: worth re-probing.
  assert spec_select_proposer("ngram", {"ngram": 0.1, "model": 0.5}, both) == ("model", 1)
  # Interactive demote bar is lower (0.15): a 0.2 EWMA alternative re-probes.
  assert spec_select_proposer("ngram", {"ngram": 0.0, "model": 0.2}, both, priority="interactive") == ("model", 1)
  # Only n-gram available (draft-free server): floor is plain.
  assert spec_select_proposer("ngram", {"ngram": 0.01}, ("ngram",)) == ("plain", 0)
  # Re-probe ranking: unmeasured first (ngram preferred), else best EWMA.
  assert spec_reprobe_proposer({}, both) == "ngram"
  assert spec_reprobe_proposer({"ngram": 0.2}, both) == "model"  # model unmeasured
  assert spec_reprobe_proposer({"ngram": 0.2, "model": 0.6}, both) == "model"
  assert spec_reprobe_proposer({"ngram": 0.7, "model": 0.6}, both) == "ngram"
  assert spec_reprobe_proposer({}, ()) is None


# ------------------------------------------------- batched identity layer


def test_spec_ngram_ab_paged_int8kv(monkeypatch):
  """A/B at the serving default (paged, int8-KV pages): n-gram spec ×
  lookahead all token-identical to solo greedy, draft-free, with real
  accepted runs (echo model on repetition-heavy prompts)."""
  monkeypatch.setenv("XOT_TPU_SPEC_NGRAM", "1")
  monkeypatch.setenv("XOT_TPU_PAGED", "1")
  monkeypatch.setenv("XOT_TPU_KV_QUANT", "int8")
  monkeypatch.setenv("XOT_TPU_PAGE_SIZE", "16")
  engine, params, shard = _engine()
  before = gm.counter_value("spec_accepted_tokens_total", labels={"proposer": "ngram"})
  _ngram_ab(engine, params, shard, PROMPTS, 10)
  assert gm.counter_value("spec_accepted_tokens_total", labels={"proposer": "ngram"}) > before


def test_spec_ngram_ab_paged_int4kv(monkeypatch):
  """Same A/B over int4-KV packed pages (ISSUE 11's layout): the verify
  window runs the packed-page write/read path."""
  monkeypatch.setenv("XOT_TPU_SPEC_NGRAM", "1")
  monkeypatch.setenv("XOT_TPU_PAGED", "1")
  monkeypatch.setenv("XOT_TPU_KV_QUANT", "int4")
  monkeypatch.setenv("XOT_TPU_PAGE_SIZE", "16")
  engine, params, shard = _engine()
  _ngram_ab(engine, params, shard, PROMPTS[:2], 8, n_slots=2)


def test_spec_ngram_ab_dense(monkeypatch):
  monkeypatch.setenv("XOT_TPU_SPEC_NGRAM", "1")
  monkeypatch.setenv("XOT_TPU_PAGED", "0")
  engine, params, shard = _engine()
  _ngram_ab(engine, params, shard, PROMPTS, 8)


def test_spec_ngram_adversarial_proposal_rejects_cleanly(monkeypatch):
  """A proposer that always proposes a WRONG continuation (suffix matches,
  continuation doesn't): every proposal rejects, output is token-identical
  to plain, positions never drift, and the pool fully recovers."""
  monkeypatch.setenv("XOT_TPU_SPEC_NGRAM", "1")
  monkeypatch.setenv("XOT_TPU_PAGED", "1")
  monkeypatch.setenv("XOT_TPU_PAGE_SIZE", "16")
  monkeypatch.setenv("XOT_TPU_SPEC_REPROBE", "1000")
  engine, params, shard = _engine()
  expected = [_single_row_reference(params, shard, p, 11, cfg=CFG) for p in PROMPTS[:2]]
  monkeypatch.setattr(
    NgramIndex, "propose",
    lambda self, k: np.asarray([(t + 1) % CFG.vocab_size for t in self.history[-min(k, 8):]], np.int32),
  )
  server = BatchedServer(engine, n_slots=2, chunk=4, lookahead=True, spec_batch=True)
  outs, streams = _serve(server, PROMPTS[:2], 12)
  assert outs == expected
  for o, s in zip(outs, streams):
    assert s == o
  for i, s in enumerate(server.slots):
    assert s is None and server._h_positions[i] == 0  # no drift into freed rows
  assert server.allocator.n_available == server.allocator.n_pages - 1
  server.shutdown()


def test_spec_ngram_sampled_rows_key_schedule_unchanged(monkeypatch):
  """Gamma-0 key-schedule identity (ISSUE 12 satellite): a seeded SAMPLED
  row's stream is identical with draft-free speculation on or off, even
  while a greedy row in the same batch rides n-gram proposals — spec chunks
  split once per round, the plain program's exact split-per-step schedule."""
  monkeypatch.setenv("XOT_TPU_SPEC_NGRAM", "1")
  monkeypatch.setenv("XOT_TPU_PAGED", "1")
  monkeypatch.setenv("XOT_TPU_PAGE_SIZE", "16")
  engine, params, shard = _engine()
  outs = {}
  for spec in (True, False):
    engine._key = jax.random.PRNGKey(123)
    server = BatchedServer(engine, n_slots=2, chunk=4, lookahead=True, spec_batch=spec)

    async def run(server=server):
      def emit(rid, toks, finished):
        pass

      return await asyncio.gather(
        server.submit("greedy", np.asarray(PROMPTS[0], np.int32), max_tokens=8, temp=0.0, top_k=35, eos_ids=(), emit=emit),
        server.submit("sampled", np.asarray([7, 1, 88], np.int32), max_tokens=8, temp=0.8, top_k=35, eos_ids=(), emit=emit),
      )

    outs[spec] = asyncio.run(run())
    server.shutdown()
  assert outs[True] == outs[False], f"sampled/greedy mix diverged: {outs[True]} != {outs[False]}"
  assert len(outs[True][1]) == 8


# ------------------------------------------------- policy convergence layer


def _spy_spec_dispatches(server):
  seen = []
  orig = server.ops.spec_paged_batch_decode

  def spy(token, pool, cache_d, bt, pos, active, gammas, *a, **k):
    pc = k.get("prop_counts")
    seen.append((np.asarray(gammas).copy(), np.asarray(pc).copy() if pc is not None else None, cache_d is not None))
    return orig(token, pool, cache_d, bt, pos, active, gammas, *a, **k)

  server.ops.spec_paged_batch_decode = spy
  return seen


def test_spec_ngram_policy_converges_nonrepetitive_to_plain(monkeypatch):
  """Monotone-spy acceptance criterion, half 1: a RANDOM model's stream
  never continues the matched suffixes, so every n-gram proposal rejects
  (or misses), the EWMA walks the depth to the floor, the row parks on
  plain, and the spec dispatches STOP — the batch no longer pays the
  verify-window or the pipeline drain."""
  monkeypatch.setenv("XOT_TPU_SPEC_NGRAM", "1")
  monkeypatch.setenv("XOT_TPU_PAGED", "1")
  monkeypatch.setenv("XOT_TPU_PAGE_SIZE", "16")
  monkeypatch.setenv("XOT_TPU_SPEC_REPROBE", "1000")  # no re-probe inside the test
  cfg = tiny_test_config(n_layers=2, max_seq_len=512, tied_embedding=True)
  engine, params, shard = _engine(cfg=cfg, key=jax.random.PRNGKey(7), echo=False)
  server = BatchedServer(engine, n_slots=1, chunk=4, lookahead=True, spec_batch=True)
  seen = _spy_spec_dispatches(server)
  prompt = [3, 25, 9, 3, 25, 9, 3, 25]  # repetitive PROMPT, non-repetitive continuation
  expected = _single_row_reference(params, shard, prompt, 79, cfg=cfg)
  outs, _ = _serve(server, [prompt], 80)
  assert outs[0] == expected
  assert seen, "n-gram speculation never dispatched (the prompt repeats; matches must fire)"
  peaks = [int(g.max()) for g, _, _ in seen]
  assert all(a >= b for a, b in zip(peaks, peaks[1:])), f"depth not monotone under rejection: {peaks}"
  assert peaks[-1] <= peaks[0]
  # The stream is 80 tokens ≈ 20 chunks; the policy stopped paying long
  # before the end (misses + rejections both charge the EWMA).
  assert len(seen) <= 10, f"batch kept paying for dead proposals: {len(seen)} spec chunks"
  assert all(not used_draft for _, _, used_draft in seen)  # draft-free program throughout
  server.shutdown()


def test_spec_ngram_policy_repetitive_row_stays_on_ngram(monkeypatch):
  """Monotone-spy acceptance criterion, half 2: the echo model's stream IS
  the repeated prompt, so proposals keep accepting and the row HOLDS
  n-gram depth — spec dispatches continue to the end of the stream with
  positive accepted counts and the proposer gauge pinned at n-gram."""
  monkeypatch.setenv("XOT_TPU_SPEC_NGRAM", "1")
  monkeypatch.setenv("XOT_TPU_PAGED", "1")
  monkeypatch.setenv("XOT_TPU_PAGE_SIZE", "16")
  cfg = tiny_test_config(n_layers=2, max_seq_len=512, tied_embedding=True)
  engine, params, shard = _engine(cfg=cfg)
  server = BatchedServer(engine, n_slots=1, chunk=4, lookahead=True, spec_batch=True)
  seen = _spy_spec_dispatches(server)
  prompt = [3, 25, 9, 7] * 3
  expected = _single_row_reference(params, shard, prompt, 63, cfg=cfg)
  before = gm.counter_value("spec_accepted_tokens_total", labels={"proposer": "ngram"})
  outs, _ = _serve(server, [prompt], 64)
  assert outs[0] == expected
  # The accepting stream rides n-gram to the END: on-stream rounds advance
  # chunk·(gamma+1) tokens per dispatch, so the whole 64-token response is
  # a handful of spec chunks — depth held at the cap, a full reference
  # stream on every dispatch, and tens of accepted tokens.
  assert seen, "repetitive row never speculated"
  assert int(seen[-1][0].max()) == server.spec_ngram_max, "depth collapsed on an accepting stream"
  assert all(pc is not None and pc.max() > 0 for _, pc, _ in seen)  # real host proposals rode every dispatch
  accepted = gm.counter_value("spec_accepted_tokens_total", labels={"proposer": "ngram"}) - before
  assert accepted >= 32, f"accepted runs should dominate the stream: {accepted}"
  server.shutdown()


def test_spec_ngram_dead_draft_switches_proposer(monkeypatch):
  """Both proposers loaded: an adversarial (≈0-acceptance) DRAFT MODEL
  collapses the model proposer; the selection policy then probes n-gram,
  which the echo stream accepts — the row converges model → n-gram instead
  of model → plain (ISSUE 12: each row converges to whichever pays)."""
  monkeypatch.setenv("XOT_TPU_SPEC_NGRAM", "1")
  monkeypatch.setenv("XOT_TPU_PAGED", "1")
  monkeypatch.setenv("XOT_TPU_PAGE_SIZE", "16")
  monkeypatch.setenv("XOT_TPU_SPEC_REPROBE", "1000")
  cfg = tiny_test_config(n_layers=2, max_seq_len=512, tied_embedding=True)
  params, shard = full_model_params(KEY, cfg, "m")
  params = peaked_echo_params(params)
  engine = JaxShardedInferenceEngine(use_local_mesh=False, spec_decode="int8")
  engine.load_test_model(shard, cfg, params)
  # Unrelated draft weights: the model proposer's acceptance is ~0.
  engine._draft_params = quantize_params(full_model_params(jax.random.PRNGKey(777), cfg, "m")[0])
  server = BatchedServer(engine, n_slots=1, chunk=4, lookahead=True, spec_batch=True)
  server._ensure_cache()
  assert server.spec_proposers == ("model", "ngram")
  seen = _spy_spec_dispatches(server)
  prompt = [3, 25, 9, 7] * 3
  expected = _single_row_reference(params, shard, prompt, 79, cfg=cfg)
  outs, _ = _serve(server, [prompt], 80)
  assert outs[0] == expected
  drafted = [i for i, (_, _, used_draft) in enumerate(seen) if used_draft]
  proposed = [i for i, (_, pc, _) in enumerate(seen) if pc is not None and pc.max() > 0]
  assert drafted, "model proposer never dispatched"
  assert proposed, "the policy never switched the row to the n-gram proposer"
  assert min(proposed) > max(drafted), f"switch order wrong: model rounds {drafted}, ngram rounds {proposed}"
  # Post-switch the n-gram proposer KEEPS paying: more n-gram dispatches
  # than the single probe, still running near the end of the stream.
  assert len(proposed) >= 3
  server.shutdown()


# ------------------------------------------------- accounting + auto layer


def test_spec_ngram_draft_free_accounting(monkeypatch):
  """ISSUE 12 satellite: draft-free speculation holds no draft KV — the
  kv_draft_* gauges read 0 and the default page pool is NOT shrunk (the
  PR 7 deduction applies only when a draft cache actually exists)."""
  monkeypatch.setenv("XOT_TPU_SPEC_NGRAM", "1")
  monkeypatch.setenv("XOT_TPU_PAGED", "1")
  monkeypatch.setenv("XOT_TPU_PAGE_SIZE", "16")
  engine, params, shard = _engine()

  server_off = BatchedServer(engine, n_slots=2, chunk=4, spec_batch=False)
  server_off._ensure_cache()
  pages_off = server_off.allocator.n_pages
  server_off.shutdown()

  server_on = BatchedServer(engine, n_slots=2, chunk=4, spec_batch=True)
  server_on._ensure_cache()
  assert server_on.spec and server_on.draft_cache is None
  assert server_on.allocator.n_pages == pages_off, "draft-free speculation must not shrink the page budget"
  assert gm.gauges.get("kv_draft_bytes") == 0
  assert gm.gauges.get("kv_draft_slots") == 0
  assert gm.gauges.get("kv_draft_pages_equivalent") == 0
  server_on.shutdown()


def test_spec_batch_auto_enables_draft_free(monkeypatch):
  """XOT_TPU_SPEC_BATCH=auto (unset) + no draft checkpoint now resolves
  speculation ON via the n-gram proposer (ISSUE 12: speculation is free to
  enable fleet-wide); XOT_TPU_SPEC_NGRAM=0 restores the PR 7 resolution
  (auto-without-draft = off, pinned in test_spec_batch)."""
  monkeypatch.setenv("XOT_TPU_SPEC_NGRAM", "1")
  monkeypatch.delenv("XOT_TPU_SPEC_BATCH", raising=False)
  engine, params, shard = _engine()
  server = BatchedServer(engine, n_slots=2, chunk=4)
  server._ensure_cache()
  assert server.spec and server.spec_proposers == ("ngram",) and server.draft_cache is None
  server.shutdown()

  monkeypatch.setenv("XOT_TPU_SPEC_NGRAM", "0")
  server2 = BatchedServer(engine, n_slots=2, chunk=4)
  server2._ensure_cache()
  assert not server2.spec and server2.spec_proposers == ()
  server2.shutdown()


# ------------------------------------------------------------- solo layer


async def _drive_stream(engine, shard, prompt, rid, chunk, max_tokens):
  """The node's chunk loop shape, including its under-delivery fallback —
  exactly what an n-gram engine's None-for-pipelining answer relies on."""
  logits, _ = await engine.infer_tensor(rid, shard, prompt)
  first = int(np.argmax(logits, -1)[0])
  out = [first]
  pending = await engine.dispatch_chunk(rid, shard, chunk, 0.0, 35, first_token=first)
  while pending is not None and len(out) < max_tokens:
    nxt = await engine.dispatch_chunk(rid, shard, chunk, 0.0, 35)
    out.extend(await engine.read_chunk(pending))
    pending = nxt
    if pending is None and len(out) < max_tokens:
      pending = await engine.dispatch_chunk(rid, shard, chunk, 0.0, 35)
  return out[:max_tokens]


@pytest.mark.asyncio
async def test_solo_spec_decode_ngram_only(monkeypatch):
  """ISSUE 12 satellite: XOT_TPU_SPEC_DECODE works with NO draft checkpoint
  configured (=ngram) — the streaming chunk path speculates from the
  session's own history, token-identical to the plain engine, with real
  accepted runs on the echo stream."""
  monkeypatch.setenv("XOT_TPU_SPEC_NGRAM", "1")
  cfg = tiny_test_config(n_layers=2, max_seq_len=256, tied_embedding=True)
  params, shard = full_model_params(jax.random.PRNGKey(11), cfg, "m")
  params = peaked_echo_params(params)
  prompt = np.array([[5, 9, 2, 71, 33, 5, 9, 2, 71, 33, 5, 9, 2]], dtype=np.int32)

  plain = JaxShardedInferenceEngine(use_local_mesh=False)
  plain.load_test_model(shard, cfg, params)
  ref = await _drive_stream(plain, shard, prompt, "a", 8, 60)

  spec = JaxShardedInferenceEngine(use_local_mesh=False, spec_decode="ngram")
  spec.load_test_model(shard, cfg, params)
  assert spec._draft_params is None
  before = gm.counter_value("spec_accepted_tokens_total", labels={"proposer": "ngram"})
  got = await _drive_stream(spec, shard, prompt, "b", 8, 60)
  assert got == ref
  assert gm.counter_value("spec_accepted_tokens_total", labels={"proposer": "ngram"}) > before
  assert spec.sessions["b"].ngram_gamma > 0  # accepting stream holds its depth


@pytest.mark.asyncio
async def test_solo_ngram_nonrepetitive_identity_and_demotion(monkeypatch):
  """Random model: proposals reject, the engine EWMA demotes to the floor,
  the session hands off to the (pipelined) plain path — and the stream is
  still token-identical throughout the transition."""
  monkeypatch.setenv("XOT_TPU_SPEC_NGRAM", "1")
  cfg = tiny_test_config(n_layers=2, max_seq_len=256, tied_embedding=True)
  params, shard = full_model_params(jax.random.PRNGKey(11), cfg, "m")
  prompt = np.array([[5, 9, 2, 71, 33, 5, 9, 2, 71, 33, 5, 9, 2]], dtype=np.int32)

  plain = JaxShardedInferenceEngine(use_local_mesh=False)
  plain.load_test_model(shard, cfg, params)
  ref = await _drive_stream(plain, shard, prompt, "a", 8, 60)

  spec = JaxShardedInferenceEngine(use_local_mesh=False, spec_decode="ngram")
  spec.load_test_model(shard, cfg, params)
  got = await _drive_stream(spec, shard, prompt, "b", 8, 60)
  assert got == ref
  sess = spec.sessions["b"]
  assert sess.ngram_gamma == 0 and sess.ngram_index is None, (
    f"rejecting stream must demote this session to plain (ewma {sess.ngram_ewma})"
  )


@pytest.mark.asyncio
async def test_solo_ngram_state_is_per_session(monkeypatch):
  """Found live (ISSUE 12 review): n-gram acceptance is a property of the
  TEXT, not the model — a non-repetitive session (e.g. the daemon's warm
  request) collapsing an ENGINE-level depth would disable speculation for
  every later session until a long re-probe streak. The state lives per
  session: after a collapsing session, the next session still opens at full
  depth and actually proposes."""
  monkeypatch.setenv("XOT_TPU_SPEC_NGRAM", "1")
  cfg = tiny_test_config(n_layers=2, max_seq_len=256, tied_embedding=True)
  params, shard = full_model_params(jax.random.PRNGKey(11), cfg, "m")
  spec = JaxShardedInferenceEngine(use_local_mesh=False, spec_decode="ngram")
  spec.load_test_model(shard, cfg, params)

  # Session 1: no suffix ever repeats — misses demote it to the floor.
  flat = np.array([[7, 12, 29, 41, 3, 88, 101, 55]], dtype=np.int32)
  await _drive_stream(spec, shard, flat, "s1", 8, 40)
  assert spec.sessions["s1"].ngram_gamma == 0 and spec.sessions["s1"].ngram_index is None

  # Session 2: repetitive prompt — proposals must still FIRE (fresh depth),
  # whatever the random model then does with them.
  before = gm.counter_value("spec_proposed_tokens_total", labels={"proposer": "ngram"})
  rep = np.array([[5, 9, 2, 5, 9, 2, 5, 9, 2, 5, 9]], dtype=np.int32)
  await _drive_stream(spec, shard, rep, "s2", 8, 24)
  assert gm.counter_value("spec_proposed_tokens_total", labels={"proposer": "ngram"}) > before, (
    "session 2 never proposed: n-gram state leaked across sessions"
  )


@pytest.mark.asyncio
async def test_solo_ngram_disabled_family_stays_plain(monkeypatch):
  """XOT_TPU_SPEC_NGRAM=0 with XOT_TPU_SPEC_DECODE=ngram: no draft, no
  n-gram — every dispatch takes the plain path (no ngram handles)."""
  monkeypatch.setenv("XOT_TPU_SPEC_NGRAM", "0")
  cfg = tiny_test_config(n_layers=2, max_seq_len=128, tied_embedding=True)
  params, shard = full_model_params(jax.random.PRNGKey(11), cfg, "m")
  engine = JaxShardedInferenceEngine(use_local_mesh=False, spec_decode="ngram")
  engine.load_test_model(shard, cfg, params)
  prompt = np.array([[5, 9, 2, 5, 9, 2]], dtype=np.int32)
  logits, _ = await engine.infer_tensor("p", shard, prompt)
  h = engine._dispatch_chunk_sync("p", shard, 8, 0.0, 35, int(np.argmax(logits, -1)[0]))
  assert not (isinstance(h, tuple) and h[0] == "ngram")
  assert engine.sessions["p"].ngram_index is None
