"""Cluster front door suite (ISSUE 13).

Covers the acceptance points end to end: the routing-policy ladder
(session → advert → weighted-least-loaded) as units, the prefix-advert TTL
staleness guard, and the REAL two-replica gRPC fixture — two full-model jax
nodes on a localhost ring (the ISSUE 10 replica-set shape, roles ``both``),
each serving its own ChatGPT API on a real TCP port, fronted by a router
(``XOT_TPU_ROUTER=1``) that owns no model:

- prefix affinity lands the request on the ADVERTISING replica (counter
  deltas + routed-target labels), token-identical to the solo baseline;
- session stickiness keeps a multi-turn chat on its replica with no advert
  round-trip;
- a replica killed MID-STREAM (transport abort — the wire-level SIGKILL)
  fails over invisibly: the client stream completes token-identical to the
  solo baseline with zero client-visible errors;
- the cluster-scoped tenant bucket refuses at 1× (not N×) aggregate quota
  while direct node access still shows the N× trust gap;
- ``XOT_TPU_ROUTER=0`` is byte-identical serving (poison pin);
- ``resume_tokens`` + ``token_stream`` (the failover building blocks) are
  pinned token-exact against the solo reference on a single replica.
"""

import asyncio
import json

import jax
import numpy as np
import pytest
from aiohttp import web
from aiohttp.test_utils import TestClient, TestServer

from tests_support_stubs import NoDiscovery, StubServer
from xotorch_support_jetson_tpu import registry
from xotorch_support_jetson_tpu.inference import router_policy, sched_admission
from xotorch_support_jetson_tpu.models.config import tiny_test_config
from xotorch_support_jetson_tpu.models.decoder import full_model_params
from xotorch_support_jetson_tpu.utils.metrics import metrics as gm

CFG = tiny_test_config(n_layers=2, max_seq_len=128)
KEY = jax.random.PRNGKey(0)
MODEL_ID = "tiny-rt"

# 16-token system prompt = 4 full pages at XOT_TPU_PAGE_SIZE=4: the shared
# prefix the affinity hash matches across requests.
SYSTEM = " ".join(str(7 + i) for i in range(16))


class _Tok:
  """Whitespace-int tokenizer: prefix-stable under multi-turn extension
  (decode∘encode is the identity on these streams), so chain keys computed
  by the router match the replicas' exactly."""

  eos_token_id = None

  def encode(self, text):
    return [int(w) for w in str(text).split()]

  def decode(self, toks):
    return " ".join(str(int(t)) for t in toks)

  def apply_chat_template(self, conversation=None, tokenize=False, add_generation_prompt=True, **kw):
    return " ".join(m["content"] for m in conversation)


_TOK = _Tok()


def _register_card(monkeypatch):
  card = registry.ModelCard(MODEL_ID, CFG.n_layers, "Tiny Router Test", "llama", {"JaxShardedInferenceEngine": "local-test"})
  monkeypatch.setitem(registry.model_cards, MODEL_ID, card)


def _messages(*contents):
  roles = ["system"] + ["user", "assistant"] * len(contents)
  return [{"role": r, "content": c} for r, c in zip(roles, contents)]


# ------------------------------------------------------------- policy units


def test_parse_replicas_forms():
  assert router_policy.parse_replicas("a=http://h:1, b=http://h:2/") == {"a": "http://h:1", "b": "http://h:2"}
  assert router_policy.parse_replicas("http://h:9") == {"h:9": "http://h:9"}
  assert router_policy.parse_replicas("") == {}
  assert router_policy.parse_replicas(None) == {} or isinstance(router_policy.parse_replicas(None), dict)


def test_policy_ladder_session_then_advert_then_load(monkeypatch):
  monkeypatch.setenv("XOT_TPU_ROUTER_AFFINITY", "1")
  monkeypatch.setenv("XOT_TPU_PREFIX_ADVERT_TTL_S", "120")
  t = [1000.0]  # nonzero: t_stats == 0 means "never pulled"
  pol = router_policy.RouterPolicy({"a": "http://a", "b": "http://b"}, clock=lambda: t[0])
  keys = [bytes([i]) * 16 for i in range(3)]
  # No stats at all: least-loaded fallback still answers.
  nid, source, hit = pol.choose(keys)
  assert nid in ("a", "b") and source == "load" and hit == 0
  # b advertises the first two keys → advert affinity.
  pol.update_stats("a", {"slots_total": 4, "slots_busy": 0, "prefix_keys": []})
  pol.update_stats("b", {"slots_total": 4, "slots_busy": 4, "prefix_keys": [k.hex() for k in keys[:2]]})
  nid, source, hit = pol.choose(keys)
  assert (nid, source, hit) == ("b", "advert", 2)
  # Session memory outranks adverts (and survives advert staleness).
  pol.note_session(keys, "a")
  nid, source, hit = pol.choose(keys)
  assert (nid, source) == ("a", "session") and hit == 3
  # Affinity off → pure least-loaded (a is idle, b is full).
  monkeypatch.setenv("XOT_TPU_ROUTER_AFFINITY", "0")
  nid, source, _ = pol.choose(keys)
  assert (nid, source) == ("a", "load")
  monkeypatch.setenv("XOT_TPU_ROUTER_AFFINITY", "1")
  # Advert TTL: past the TTL the advert stops steering (the staleness
  # guard), and the session entry for an excluded replica is skipped too.
  pol2 = router_policy.RouterPolicy({"a": "http://a", "b": "http://b"}, clock=lambda: t[0])
  pol2.update_stats("b", {"prefix_keys": [k.hex() for k in keys]})
  t[0] += 121.0
  nid, source, _ = pol2.choose(keys)
  assert source == "load"
  # Draining replicas are ineligible; exclusion falls through to survivors.
  pol.update_stats("a", {"draining": True})
  nid, _, _ = pol.choose(keys)
  assert nid == "b"
  assert pol.choose(keys, exclude={"a", "b"})[0] is None


def test_cluster_retry_horizon_is_min_over_replicas():
  pol = router_policy.RouterPolicy({"a": "http://a", "b": "http://b", "c": "http://c"})
  assert pol.cluster_retry_after_ms() == 1000.0  # cold: nothing advertised
  pol.update_stats("a", {"est_drain_ms": 5000.0})
  pol.update_stats("b", {"est_drain_ms": 800.0})
  assert pol.cluster_retry_after_ms() == 800.0  # soonest ANY replica drains
  pol.update_stats("c", {"ttft_p50_ms": 100.0, "queue_depth_total": 2, "slots_total": 4})
  assert pol.cluster_retry_after_ms() == 150.0  # ttft-scaled pseudo-estimate


def test_load_score_orders_pressure():
  idle = {"slots_total": 4, "slots_busy": 0, "queue_depth_total": 0, "total_pages": 100, "free_pages": 90}
  busy = {"slots_total": 4, "slots_busy": 4, "queue_depth_total": 8, "total_pages": 100, "free_pages": 5}
  assert sched_admission.load_score(idle) < sched_admission.load_score(busy)
  # Burn contributes: same capacity, one replica burning error budget.
  hot = dict(idle, slo_burn_fast={"interactive": 10.0})
  assert sched_admission.load_score(idle) < sched_admission.load_score(hot)
  # rank_* heads stay the historical choose_* answers (pinned in
  # test_disagg); the ranked pools expose the N×M tail.
  stats = {
    "d1": {"role": "decode", "free_pages": 10, "queue_depth": 3},
    "d2": {"role": "decode", "free_pages": 40, "queue_depth": 5},
    "b1": {"role": "both", "free_pages": 500, "queue_depth": 0},
  }
  ranked = sched_admission.rank_decode_nodes(stats, self_id="me", self_role="prefill")
  assert ranked == ["d2", "d1", "b1"]
  assert sched_admission.choose_decode_node(stats, self_id="me", self_role="prefill") == "d2"


def test_prefix_registry_advert_ttl(monkeypatch):
  from xotorch_support_jetson_tpu.inference.kv_tier import PrefixRegistry

  monkeypatch.setenv("XOT_TPU_PREFIX_ADVERT_TTL_S", "10")
  t = [0.0]
  reg = PrefixRegistry(clock=lambda: t[0])
  key = b"\x01" * 16
  reg.update_remote("peer-a", [key.hex()])
  assert reg.locate(key) == ["peer-a"]
  assert reg.stale_remote_ids() == []
  t[0] = 10.5  # past the TTL: the advert stops steering and asks for a re-pull
  assert reg.locate(key) == []
  assert reg.stale_remote_ids() == ["peer-a"]
  snap = reg.snapshot()
  assert snap["stale"] == ["peer-a"] and snap["remote_age_s"]["peer-a"] == 10.5
  reg.update_remote("peer-a", [key.hex()])  # the re-pull restores steering
  assert reg.locate(key) == ["peer-a"] and reg.stale_remote_ids() == []
  monkeypatch.setenv("XOT_TPU_PREFIX_ADVERT_TTL_S", "0")  # 0 disables expiry
  t[0] = 1e6
  assert reg.locate(key) == ["peer-a"]


# --------------------------------------------------- two-replica gRPC fixture


def _fixture_env(monkeypatch):
  _register_card(monkeypatch)
  monkeypatch.setenv("XOT_TPU_BATCHED", "1")
  monkeypatch.setenv("XOT_TPU_PAGE_SIZE", "4")
  monkeypatch.setenv("XOT_TPU_BATCH_CHUNK", "2")
  # The ISSUE 10 replica-set shape: a ring where every node holds the FULL
  # model (roles default to ``both`` → each serves colocated; two ``both``
  # peers never hand off to each other).
  monkeypatch.setenv("XOT_TPU_DISAGG", "1")
  monkeypatch.setenv("XOT_TPU_RETRY_DELAY_S", "0.05")
  # One stats pull per test: session-vs-advert attribution stays
  # deterministic (the sticky test must hit the SESSION path, not a
  # freshly refreshed advert).
  monkeypatch.setenv("XOT_TPU_ROUTER_STATS_TTL_S", "60")


async def _make_replica_ring(monkeypatch, ids, ports):
  """Two full-model jax nodes on a localhost gRPC ring, each with its own
  ChatGPT API bound to a real TCP port."""
  from xotorch_support_jetson_tpu.api.chatgpt_api import ChatGPTAPI
  from xotorch_support_jetson_tpu.inference.jax_engine import JaxShardedInferenceEngine
  from xotorch_support_jetson_tpu.networking.grpc.grpc_peer_handle import GRPCPeerHandle
  from xotorch_support_jetson_tpu.networking.grpc.grpc_server import GRPCServer
  from xotorch_support_jetson_tpu.orchestration.node import Node
  from xotorch_support_jetson_tpu.topology.partitioning import RingMemoryWeightedPartitioningStrategy
  from tests.test_networking import CAPS, StaticDiscovery
  from xotorch_support_jetson_tpu.utils.helpers import find_available_port

  params, shard = full_model_params(KEY, CFG, MODEL_ID)
  nodes, apis, runners, urls = [], [], [], []
  for i in range(2):
    engine = JaxShardedInferenceEngine(use_local_mesh=False)
    engine.load_test_model(shard, CFG, params, tokenizer=_Tok())
    peers = [GRPCPeerHandle(ids[j], f"127.0.0.1:{ports[j]}", "test", CAPS) for j in range(2) if j != i]
    node = Node(
      ids[i], None, engine, StaticDiscovery(peers), None,
      RingMemoryWeightedPartitioningStrategy(), max_generate_tokens=200, default_sample_temp=0.0,
    )
    node.server = GRPCServer(node, "127.0.0.1", ports[i])
    nodes.append(node)
  await asyncio.gather(*(n.start() for n in nodes))
  for _ in range(100):
    if all(len(n.topology.nodes) == 2 for n in nodes):
      break
    await asyncio.gather(*(n.collect_topology(set()) for n in nodes))
    await asyncio.sleep(0.05)
  for node in nodes:
    api = ChatGPTAPI(node, "JaxShardedInferenceEngine", response_timeout=60, default_model=MODEL_ID)
    server = TestServer(api.app)
    await server.start_server()
    apis.append(api)
    runners.append(server)
    urls.append(str(server.make_url("")).rstrip("/"))
  return params, shard, nodes, apis, runners, urls


async def _make_router(monkeypatch, ids, urls):
  """An API-only router node: owns no model (only the tokenizer), fronting
  the replica URLs."""
  from xotorch_support_jetson_tpu.api.chatgpt_api import ChatGPTAPI
  from xotorch_support_jetson_tpu.inference.dummy_engine import DummyInferenceEngine
  from xotorch_support_jetson_tpu.orchestration.node import Node
  from xotorch_support_jetson_tpu.topology.partitioning import RingMemoryWeightedPartitioningStrategy

  monkeypatch.setenv("XOT_TPU_ROUTER", "1")
  monkeypatch.setenv("XOT_TPU_ROUTER_REPLICAS", ",".join(f"{i}={u}" for i, u in zip(ids, urls)))
  node = Node("rt-router", StubServer(), DummyInferenceEngine(), NoDiscovery(), None, RingMemoryWeightedPartitioningStrategy())
  await node.start()
  api = ChatGPTAPI(node, "JaxShardedInferenceEngine", response_timeout=60, default_model=MODEL_ID)
  assert api._router is not None

  async def _tok(shard):
    return _TOK

  api._tokenizer_for = _tok  # the router resolves tokenizer artifacts, never weights
  client = TestClient(TestServer(api.app))
  await client.start_server()
  return node, api, client


async def _teardown(nodes, runners, router=None):
  if router is not None:
    node, api, client = router
    if api._router is not None:
      await api._router.close()
    await client.close()
    await node.stop()
  for r in runners:
    try:
      await asyncio.wait_for(r.close(), timeout=5)
    except asyncio.TimeoutError:
      pass
  for n in nodes:
    server = getattr(n.inference_engine, "_batched_server", None)
    if server is not None:
      server.shutdown()
    await n.stop()


def _reference(params, shard, prompt_ids, n_tokens):
  from tests.test_batched import _single_row_reference

  return _single_row_reference(params, shard, list(prompt_ids), n_tokens - 1)


async def _sse_text(resp):
  """Accumulate an OpenAI chat SSE stream → (text, saw_error)."""
  acc, err = "", False
  async for line in resp.content:
    line = line.decode().strip()
    if not line.startswith("data: ") or line == "data: [DONE]":
      continue
    obj = json.loads(line[6:])
    if "error" in obj:
      err = True
      continue
    delta = (obj.get("choices") or [{}])[0].get("delta", {}).get("content")
    if delta:
      acc += delta
  return acc, err


def _target_counts(ids):
  return {i: gm.counter_value("router_requests_total", labels={"target": i}) for i in ids}


@pytest.mark.asyncio
async def test_router_affinity_session_and_state(monkeypatch):
  """Acceptance: (1) a request whose system-prompt KV sits on replica A is
  routed to A by the ADVERT hash and token-matches the solo baseline;
  (2) the follow-up turn sticks to its replica via SESSION affinity with no
  advert refresh; (3) /v1/router and /v1/router/stats surface the state."""
  _fixture_env(monkeypatch)
  from xotorch_support_jetson_tpu.utils.helpers import find_available_port

  ids = ["rtaff0", "rtaff1"]
  ports = [find_available_port("127.0.0.1") for _ in range(2)]
  params, shard, nodes, apis, runners, urls = await _make_replica_ring(monkeypatch, ids, ports)
  router = await _make_router(monkeypatch, ids, urls)
  _node_r, api_r, client = router
  try:
    import aiohttp

    # Warm replica A DIRECTLY (not through the router): its finished
    # request donates the system-prompt pages to A's prefix cache.
    async with aiohttp.ClientSession() as s:
      body = {"model": MODEL_ID, "messages": _messages(SYSTEM, "1 2 3"), "max_tokens": 4}
      async with s.post(urls[0] + "/v1/chat/completions", json=body) as resp:
        assert resp.status == 200, await resp.text()
      # The replica advertises its prefix keys at the stats endpoint.
      async with s.get(urls[0] + "/v1/router/stats") as resp:
        st = await resp.json()
        assert st["node_id"] == ids[0] and st["page_size"] == 4
        assert len(st["prefix_keys"]) >= 4  # 16-token system prompt = 4 pages (+ donated tail)

    # A DIFFERENT conversation sharing the system prompt, via the router:
    # the advert hash must land it on A (where the KV sits).
    before = _target_counts(ids)
    hits_before = gm.counter_value("router_prefix_hits_total", labels={"source": "advert"})
    prompt_ids = _TOK.encode(" ".join([SYSTEM, "9 8 7 6"]))
    expected = _reference(params, shard, prompt_ids, 6)
    resp = await client.post("/v1/chat/completions", json={"model": MODEL_ID, "messages": _messages(SYSTEM, "9 8 7 6"), "max_tokens": 6})
    assert resp.status == 200, await resp.text()
    data = await resp.json()
    assert data["choices"][0]["message"]["content"] == _TOK.decode(expected)
    after = _target_counts(ids)
    assert after[ids[0]] == before[ids[0]] + 1 and after[ids[1]] == before[ids[1]]
    assert gm.counter_value("router_prefix_hits_total", labels={"source": "advert"}) == hits_before + 1

    # Follow-up turn: extends the conversation → SESSION stickiness (the
    # stats TTL guarantees no advert refresh happened in between).
    sess_before = gm.counter_value("router_prefix_hits_total", labels={"source": "session"})
    turn2 = _messages(SYSTEM, "9 8 7 6", data["choices"][0]["message"]["content"], "5 5")
    prompt2_ids = _TOK.encode(" ".join(m["content"] for m in turn2))
    expected2 = _reference(params, shard, prompt2_ids, 5)
    resp = await client.post("/v1/chat/completions", json={"model": MODEL_ID, "messages": turn2, "max_tokens": 5, "stream": True})
    assert resp.status == 200
    text2, saw_err = await _sse_text(resp)
    assert not saw_err and text2 == _TOK.decode(expected2)
    after2 = _target_counts(ids)
    assert after2[ids[0]] == after[ids[0]] + 1  # stuck to A
    assert gm.counter_value("router_prefix_hits_total", labels={"source": "session"}) == sess_before + 1

    # Router introspection.
    resp = await client.get("/v1/router")
    state = await resp.json()
    assert state["enabled"] and set(state["replicas"]) == set(ids)
    assert state["replicas"][ids[0]]["prefix_keys"] >= 4
    # The replica's own view of router mode is off.
    async with aiohttp.ClientSession() as s:
      async with s.get(urls[0] + "/v1/router") as resp:
        assert (await resp.json())["enabled"] is False
  finally:
    await _teardown(nodes, runners, router)


@pytest.mark.asyncio
async def test_router_failover_mid_stream_token_identical(monkeypatch):
  """Acceptance: kill the serving replica MID-STREAM (transport abort — the
  wire-level SIGKILL). The router re-submits the remainder to the survivor
  with ``resume_tokens`` and splices the continuation: the client sees ONE
  unbroken stream, token-identical to the solo baseline, zero errors."""
  _fixture_env(monkeypatch)
  from xotorch_support_jetson_tpu.utils.helpers import find_available_port

  ids = ["rtko0", "rtko1"]
  ports = [find_available_port("127.0.0.1") for _ in range(2)]
  params, shard, nodes, apis, runners, urls = await _make_replica_ring(monkeypatch, ids, ports)
  router = await _make_router(monkeypatch, ids, urls)
  _node_r, api_r, client = router
  try:
    import aiohttp

    # Pin the victim: warm A so affinity routes the doomed request there.
    async with aiohttp.ClientSession() as s:
      async with s.post(urls[0] + "/v1/chat/completions", json={"model": MODEL_ID, "messages": _messages(SYSTEM, "2 4"), "max_tokens": 3}) as resp:
        assert resp.status == 200

    n_tokens = 24  # XOT_TPU_BATCH_CHUNK=2 → many chunks → a real mid-stream kill window
    prompt_ids = _TOK.encode(" ".join([SYSTEM, "11 12 13"]))
    expected = _reference(params, shard, prompt_ids, n_tokens)
    failovers_before = gm.counter_value("router_failovers_total")
    before = _target_counts(ids)

    resp = await client.post(
      "/v1/chat/completions",
      json={"model": MODEL_ID, "messages": _messages(SYSTEM, "11 12 13"), "max_tokens": n_tokens, "stream": True},
    )
    assert resp.status == 200
    acc, saw_err, killed = "", False, False
    async for line in resp.content:
      line = line.decode().strip()
      if not line.startswith("data: ") or line == "data: [DONE]":
        continue
      obj = json.loads(line[6:])
      if "error" in obj:
        saw_err = True
        continue
      delta = (obj.get("choices") or [{}])[0].get("delta", {}).get("content")
      if delta:
        acc += delta
      if not killed and len(_TOK.encode(acc)) >= 4:
        killed = True
        # SIGKILL at the wire: abort every live connection into replica A
        # and stop its listener — the router's read fails mid-stream.
        web_server = runners[0].runner.server
        for proto in list(getattr(web_server, "connections", []) or []):
          tr = getattr(proto, "transport", None)
          if tr is not None:
            tr.abort()
        for site in list(runners[0].runner.sites):
          await site.stop()
    assert killed, "stream finished before the kill window — raise n_tokens"
    assert not saw_err, "failover leaked a client-visible error"
    assert acc == _TOK.decode(expected), f"spliced stream diverged: {acc!r}"
    assert gm.counter_value("router_failovers_total") == failovers_before + 1
    after = _target_counts(ids)
    assert after[ids[0]] == before[ids[0]] + 1  # the doomed dispatch
    assert after[ids[1]] == before[ids[1]] + 1  # the survivor's resume
    # The survivor's scheduler finished clean.
    srv_b = nodes[1].inference_engine.get_batched_server()
    assert all(s is None for s in srv_b.slots)
  finally:
    await _teardown(nodes, runners, router)


@pytest.mark.asyncio
async def test_cluster_tenant_bucket_refuses_at_aggregate_quota(monkeypatch):
  """Acceptance: the router enforces ONE logical tenant bucket for the
  fleet — the tenant is refused at 1× the aggregate quota, while direct
  node access still grants the N× the PR 5 trust note warned about."""
  _fixture_env(monkeypatch)
  monkeypatch.setenv("XOT_TPU_QOS_RPS", "2")
  monkeypatch.setenv("XOT_TPU_QOS_BURST_S", "1")
  from xotorch_support_jetson_tpu.utils.helpers import find_available_port

  ids = ["rtten0", "rtten1"]
  ports = [find_available_port("127.0.0.1") for _ in range(2)]
  params, shard, nodes, apis, runners, urls = await _make_replica_ring(monkeypatch, ids, ports)
  router = await _make_router(monkeypatch, ids, urls)
  _node_r, api_r, client = router
  try:
    throttled_before = gm.counter_value("router_tenant_throttled_total", labels={"tenant": "acme"})
    body = {"model": MODEL_ID, "messages": _messages(SYSTEM, "3 1"), "max_tokens": 2}
    headers = {"x-tenant-id": "acme"}
    for _ in range(2):  # the aggregate quota: 2 requests
      resp = await client.post("/v1/chat/completions", json=body, headers=headers)
      assert resp.status == 200, await resp.text()
    resp = await client.post("/v1/chat/completions", json=body, headers=headers)
    assert resp.status == 429
    refusal = await resp.json()
    assert refusal["error"]["type"] == "rate_limited"
    assert "Retry-After" in resp.headers
    assert gm.counter_value("router_tenant_throttled_total", labels={"tenant": "acme"}) == throttled_before + 1
    # The SAME tenant hitting a node DIRECTLY still gets fresh per-node
    # quota — the N× trust gap the router closes.
    import aiohttp

    async with aiohttp.ClientSession() as s:
      async with s.post(urls[1] + "/v1/chat/completions", json=body, headers=headers) as direct:
        assert direct.status == 200
  finally:
    await _teardown(nodes, runners, router)


@pytest.mark.asyncio
async def test_resume_tokens_and_token_stream_pins(monkeypatch):
  """The failover building blocks, pinned on one replica: ``token_stream``
  streams raw token-id batches, and ``resume_tokens`` continues the stream
  token-exactly where the carried span ends (the scheduler's carry-resume
  surfaced at the API)."""
  _fixture_env(monkeypatch)
  from xotorch_support_jetson_tpu.utils.helpers import find_available_port

  ids = ["rtres0", "rtres1"]
  ports = [find_available_port("127.0.0.1") for _ in range(2)]
  params, shard, nodes, apis, runners, urls = await _make_replica_ring(monkeypatch, ids, ports)
  try:
    import aiohttp

    n_tokens = 12
    prompt_ids = _TOK.encode(" ".join([SYSTEM, "42 17"]))
    expected = _reference(params, shard, prompt_ids, n_tokens)

    async def token_stream(body) -> list[int]:
      got: list[int] = []
      async with aiohttp.ClientSession() as s:
        async with s.post(urls[0] + "/v1/chat/completions", json=body) as resp:
          assert resp.status == 200, await resp.text()
          async for line in resp.content:
            line = line.decode().strip()
            if not line.startswith("data: ") or line == "data: [DONE]":
              continue
            obj = json.loads(line[6:])
            assert "error" not in obj, obj
            got.extend(obj["tokens"])
      return got

    base = {"model": MODEL_ID, "messages": _messages(SYSTEM, "42 17"), "stream": True, "token_stream": True}
    assert await token_stream({**base, "max_tokens": n_tokens}) == expected
    # Resume after k carried tokens: the continuation is exactly the tail.
    k = 5
    resumed = await token_stream({**base, "max_tokens": n_tokens - k, "resume_tokens": expected[:k]})
    assert resumed == expected[k:]
    # Malformed resume payload is a clean 400.
    async with aiohttp.ClientSession() as s:
      async with s.post(urls[0] + "/v1/chat/completions", json={**base, "resume_tokens": ["x"]}) as resp:
        assert resp.status == 400
  finally:
    await _teardown(nodes, runners)


# ----------------------------------------------- stub-replica pump behavior


def _stub_replica_app(node_id: str, *, refuse_429: bool = False, tokens=(5, 6), est_drain_ms=None):
  """A fake replica speaking just enough of the protocol: /v1/router/stats
  and a token-stream completions endpoint (or a structured 429)."""
  served = {"n": 0, "bodies": []}

  async def stats(request):
    st = {"node_id": node_id, "slots_total": 2, "slots_busy": 0, "page_size": 4, "prefix_keys": []}
    if est_drain_ms is not None:
      st["est_drain_ms"] = est_drain_ms
    return web.json_response(st)

  async def completions(request):
    served["n"] += 1
    served["bodies"].append(await request.json())
    if refuse_429:
      return web.json_response(
        {"error": {"type": "overloaded", "message": "queue full", "retry_after_ms": 60000.0}},
        status=429, headers={"Retry-After": "60"},
      )
    resp = web.StreamResponse(headers={"Content-Type": "text/event-stream"})
    await resp.prepare(request)
    await resp.write(f"data: {json.dumps({'tokens': list(tokens), 'finished': True})}\n\n".encode())
    await resp.write(b"data: [DONE]\n\n")
    await resp.write_eof()
    return resp

  app = web.Application()
  app.router.add_get("/v1/router/stats", stats)
  app.router.add_post("/v1/chat/completions", completions)
  return app, served


async def _stub_router(monkeypatch, stubs):
  from xotorch_support_jetson_tpu.api.chatgpt_api import ChatGPTAPI
  from xotorch_support_jetson_tpu.inference.dummy_engine import DummyInferenceEngine
  from xotorch_support_jetson_tpu.orchestration.node import Node
  from xotorch_support_jetson_tpu.topology.partitioning import RingMemoryWeightedPartitioningStrategy
  from xotorch_support_jetson_tpu.utils.helpers import find_available_port

  _register_card(monkeypatch)
  runners, entries = [], []
  for node_id, app in stubs:
    runner = web.AppRunner(app)
    await runner.setup()
    port = find_available_port("127.0.0.1")
    await web.TCPSite(runner, "127.0.0.1", port).start()
    runners.append(runner)
    entries.append(f"{node_id}=http://127.0.0.1:{port}")
  monkeypatch.setenv("XOT_TPU_ROUTER", "1")
  monkeypatch.setenv("XOT_TPU_ROUTER_REPLICAS", ",".join(entries))
  node = Node("rt-stub-router", StubServer(), DummyInferenceEngine(), NoDiscovery(), None, RingMemoryWeightedPartitioningStrategy())
  await node.start()
  api = ChatGPTAPI(node, "JaxShardedInferenceEngine", response_timeout=30, default_model=MODEL_ID)

  async def _tok(shard):
    return _TOK

  api._tokenizer_for = _tok
  client = TestClient(TestServer(api.app))
  await client.start_server()
  return node, api, client, runners


@pytest.mark.asyncio
async def test_router_tries_next_replica_on_429(monkeypatch):
  """One replica's full queue is NOT cluster overload: the router moves on
  to a survivor and the client never sees the refusal."""
  app_full, served_full = _stub_replica_app("stub-full", refuse_429=True)
  app_ok, served_ok = _stub_replica_app("stub-ok", tokens=(5, 6, 7))
  node, api, client, runners = await _stub_router(monkeypatch, [("stub-full", app_full), ("stub-ok", app_ok)])
  try:
    resp = await client.post("/v1/chat/completions", json={"model": MODEL_ID, "messages": _messages("1 2 3 4", "5"), "max_tokens": 3})
    assert resp.status == 200, await resp.text()
    data = await resp.json()
    assert data["choices"][0]["message"]["content"] == "5 6 7"
    assert served_ok["n"] == 1
  finally:
    if api._router is not None:
      await api._router.close()
    await client.close()
    await node.stop()
    for r in runners:
      await r.cleanup()


@pytest.mark.asyncio
async def test_router_relays_client_resume_and_refuses_images(monkeypatch):
  """A client re-submitting the router's own terminal 503 contract gets its
  ``resume_tokens`` RELAYED (carried downstream, never re-delivered, and
  max_tokens NOT double-decremented — the client already sent the remaining
  budget); image content gets an explicit 400, not model-less local
  serving."""
  app_ok, served = _stub_replica_app("stub-res", tokens=(7,))
  node, api, client, runners = await _stub_router(monkeypatch, [("stub-res", app_ok)])
  try:
    body = {
      "model": MODEL_ID, "messages": _messages("1 2 3 4", "5"),
      "max_tokens": 3, "resume_tokens": [5, 6],
    }
    resp = await client.post("/v1/chat/completions", json=body)
    assert resp.status == 200, await resp.text()
    data = await resp.json()
    assert data["choices"][0]["message"]["content"] == "7"  # carried span not re-delivered
    fwd = served["bodies"][0]
    assert fwd["resume_tokens"] == [5, 6] and fwd["max_tokens"] == 3 and fwd["token_stream"] is True
    # Image content: explicit refusal (a model-less front door must not
    # fall through to local serving).
    img_msg = [{"role": "user", "content": [{"type": "image_url", "image_url": {"url": "data:image/png;base64,aGk="}}]}]
    resp = await client.post("/v1/chat/completions", json={"model": "llava-1.5-7b-hf", "messages": img_msg})
    assert resp.status == 400
    assert "router" in (await resp.json())["error"]
  finally:
    if api._router is not None:
      await api._router.close()
    await client.close()
    await node.stop()
    for r in runners:
      await r.cleanup()


@pytest.mark.asyncio
async def test_router_429_carries_cluster_retry_horizon(monkeypatch):
  """Satellite: when the WHOLE fleet refuses, the relayed 429 carries the
  CLUSTER retry horizon (the soonest any replica drains — 800 ms here),
  not the refusing node's own 60 s estimate."""
  app_a, _ = _stub_replica_app("stub-a", refuse_429=True, est_drain_ms=5000.0)
  app_b, _ = _stub_replica_app("stub-b", refuse_429=True, est_drain_ms=800.0)
  node, api, client, runners = await _stub_router(monkeypatch, [("stub-a", app_a), ("stub-b", app_b)])
  try:
    resp = await client.post("/v1/chat/completions", json={"model": MODEL_ID, "messages": _messages("1 2 3 4", "5"), "max_tokens": 3})
    assert resp.status == 429
    body = await resp.json()
    assert body["error"]["type"] == "overloaded"
    assert body["error"]["retry_after_ms"] == 800.0  # cluster horizon, not 60000
    assert resp.headers["Retry-After"] == "1"
  finally:
    if api._router is not None:
      await api._router.close()
    await client.close()
    await node.stop()
    for r in runners:
      await r.cleanup()


@pytest.mark.asyncio
async def test_router_off_is_byte_identical_serving(monkeypatch):
  """XOT_TPU_ROUTER unset/0: no router is constructed and NO router code
  runs on the request path (poisoned policy + transport never called)."""
  from xotorch_support_jetson_tpu.api import router as api_router
  from xotorch_support_jetson_tpu.api.chatgpt_api import ChatGPTAPI
  from xotorch_support_jetson_tpu.inference.dummy_engine import DummyInferenceEngine
  from xotorch_support_jetson_tpu.orchestration.node import Node
  from xotorch_support_jetson_tpu.topology.partitioning import RingMemoryWeightedPartitioningStrategy

  monkeypatch.delenv("XOT_TPU_ROUTER", raising=False)
  monkeypatch.delenv("XOT_TPU_ROUTER_REPLICAS", raising=False)

  def poisoned(*a, **k):  # noqa: ANN001
    raise AssertionError("router code ran with XOT_TPU_ROUTER off")

  monkeypatch.setattr(api_router.ClusterRouter, "serve_chat", poisoned)
  monkeypatch.setattr(router_policy.RouterPolicy, "choose", poisoned)

  node = Node("rt-off-node", StubServer(), DummyInferenceEngine(), NoDiscovery(), None, RingMemoryWeightedPartitioningStrategy())
  await node.start()
  api = ChatGPTAPI(node, "DummyInferenceEngine", default_model="dummy")
  assert api._router is None
  client = TestClient(TestServer(api.app))
  await client.start_server()
  try:
    resp = await client.post("/v1/chat/completions", json={"model": "dummy", "messages": [{"role": "user", "content": "hi"}], "max_tokens": 4})
    assert resp.status == 200
    resp = await client.get("/v1/router")
    assert (await resp.json())["enabled"] is False
  finally:
    await client.close()
    await node.stop()
