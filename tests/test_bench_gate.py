"""bench.py integrity guards (VERDICT r2 weak #1).

The round-2 driver artifact recorded a headline of 79,922.77 tok/s — a
``jax.block_until_ready`` tunnel artifact ~360x the HBM roofline — while the
same run's serving path measured 216.04. These tests pin the two guards that
keep that class of error out of the judged record: the headline sanity gate
and the plausibility filter used for the ``vs_baseline`` denominator.
"""

import json
import pathlib
import sys

import pytest

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))

from bench import gate_disagg, gate_failover, gate_headline, gate_kv_tier, gate_lookahead, gate_lora, gate_overload, gate_slo, gate_spec_batch, plausible_value

# The actual poisoned round-2 record (BENCH_r02.json "parsed" payload).
R02 = {
  "metric": "decode_tokens_per_sec_llama1b_bf16_1chip",
  "value": 79922.77,
  "unit": "tokens/s",
  "serving_chunked_tok_s": 216.04,
}
# The honest round-1 record.
R01 = {
  "metric": "decode_tokens_per_sec_llama1b_bf16_1chip",
  "value": 220.69,
  "unit": "tokens/s",
  "serving_chunked_tok_s": 221.35,
}


def test_gate_fires_on_fake_fast_headline():
  value, tripped = gate_headline(79922.77, 216.04)
  assert tripped
  assert value == 216.04


def test_gate_passes_honest_headline():
  value, tripped = gate_headline(220.69, 221.35)
  assert not tripped
  assert value == 220.69
  # Mild skew (decode slightly faster than chunked serving) is real, not an
  # artifact: the serving path adds scheduling overhead.
  value, tripped = gate_headline(300.0, 220.0)
  assert not tripped and value == 300.0


def test_gate_without_serving_reference_is_identity():
  value, tripped = gate_headline(500.0, None)
  assert not tripped and value == 500.0


def test_plausible_value_rejects_poisoned_r02_record():
  assert plausible_value(R02) == 216.04


def test_plausible_value_keeps_honest_record():
  assert plausible_value(R01) == 220.69


def test_plausible_value_handles_missing_fields():
  assert plausible_value({}) is None
  assert plausible_value({"value": 100.0}) == 100.0


def test_lookahead_gate_keeps_plausible_ratios():
  """batch48_lookahead_vs_sync rides the same drift-gate pattern: overlap
  can only hide the per-chunk host window, so honest ratios sit near 1."""
  assert gate_lookahead(1.08) == 1.08
  assert gate_lookahead(0.97) == 0.97
  assert gate_lookahead(2.9) == 2.9


def test_lookahead_gate_drops_artifacts():
  # A 360x-style block_until_ready artifact on one side of the A/B cannot
  # enter the tracked record as a "scheduling win" (or loss).
  assert gate_lookahead(12.4) is None
  assert gate_lookahead(0.05) is None
  assert gate_lookahead(None) is None


def test_overload_gate_keeps_plausible_shed_rates():
  """The QoS overload round's shed rate is a fraction of offered load: a
  healthy 2x-overload run sheds some batch work, never (nearly) all of it."""
  assert gate_overload(0.0) == 0.0
  assert gate_overload(0.25) == 0.25
  assert gate_overload(0.9) == 0.9


def test_slo_gate_keeps_fractions_and_drops_artifacts():
  """ISSUE 9: attainment and goodput ratio are counter-delta fractions —
  [0, 1] exactly (1.0 is a legitimately perfect round and must survive the
  gate); outside means the delta went negative across a registry reset."""
  assert gate_slo(0.0) == 0.0
  assert gate_slo(0.97) == 0.97
  assert gate_slo(1.0) == 1.0
  assert gate_slo(1.2) is None
  assert gate_slo(-0.1) is None
  assert gate_slo(None) is None


def test_failover_gate_keeps_plausible_recoveries():
  """ISSUE 8: kill-to-next-token recovery on the localhost drill is the
  replay delay plus one re-prefill — tens of ms to tens of seconds."""
  assert gate_failover(250.0) == 250.0
  assert gate_failover(3200.5) == 3200.5
  assert gate_failover(1.0) == 1.0


def test_failover_gate_drops_artifacts():
  """Sub-millisecond recovery means a token raced the kill; beyond 120 s the
  stream wedged into an outer timeout — both dropped, not recorded."""
  assert gate_failover(0.2) is None
  assert gate_failover(500000.0) is None
  assert gate_failover(None) is None


def test_kv_tier_gate_keeps_plausible_values():
  """ISSUE 6: spill/restore bandwidths inside [0.01, 1000] GB/s pass
  through unchanged; the resume A/B ratio rides the same gate with its own
  bounds."""
  assert gate_kv_tier(1.5) == 1.5
  assert gate_kv_tier(80.0) == 80.0
  assert gate_kv_tier(0.01) == 0.01
  assert gate_kv_tier(3.7, lo=1.0 / 3.0, hi=100.0) == 3.7


def test_kv_tier_gate_drops_artifacts():
  """A PCIe copy cannot run at terabytes/s (early block_until_ready return)
  or at ~zero (tunnel stall) — both are timing artifacts, dropped rather
  than recorded."""
  assert gate_kv_tier(2000.0) is None
  assert gate_kv_tier(0.0) is None
  assert gate_kv_tier(-1.0) is None
  assert gate_kv_tier(None) is None
  assert gate_kv_tier(500.0, lo=1.0 / 3.0, hi=100.0) is None


def test_overload_gate_drops_artifacts():
  # A wedged scheduler shedding the world (or a counter going negative
  # across a registry reset) must not enter the tracked record.
  assert gate_overload(1.0) is None
  assert gate_overload(0.99) is None
  assert gate_overload(-0.1) is None
  assert gate_overload(None) is None


def test_spec_batch_gate_keeps_plausible_ratios():
  """ISSUE 7: the batched-spec/plain A/B ratio lives in ~[0.5, gamma+1] —
  parity-ish at the adaptive floor, up to ~5x at full acceptance/gamma 4."""
  assert gate_spec_batch(1.0) == 1.0
  assert gate_spec_batch(0.6) == 0.6
  assert gate_spec_batch(3.4) == 3.4
  assert gate_spec_batch(7.9) == 7.9


def test_spec_batch_gate_drops_artifacts():
  # An early block_until_ready return on one side of the A/B must not enter
  # the record as a 50x "speculation win" (or a near-zero collapse).
  assert gate_spec_batch(50.0) is None
  assert gate_spec_batch(0.05) is None
  assert gate_spec_batch(None) is None


def test_spec_ngram_gate_keeps_plausible_ratios():
  """ISSUE 12: the draft-free n-gram/plain A/B ratio lives in ~[0.5, 9] —
  parity-ish at the adaptive floor, up to ~gamma+1 (benched depth 8) when
  on-stream rounds keep full acceptance on the repetition-heavy workload."""
  from bench import gate_spec_ngram

  assert gate_spec_ngram(1.0) == 1.0
  assert gate_spec_ngram(0.6) == 0.6
  assert gate_spec_ngram(4.2) == 4.2
  assert gate_spec_ngram(11.5) == 11.5


def test_spec_ngram_gate_drops_artifacts():
  from bench import gate_spec_ngram

  assert gate_spec_ngram(60.0) is None
  assert gate_spec_ngram(0.05) is None
  assert gate_spec_ngram(None) is None


def test_spec_policy_verdicts_pinned():
  """The proposer-policy dispatch verdicts bench emits on EVERY round
  (non-null on CPU, the paged_tile_* pattern): a collapsed model proposer
  switches to the untried n-gram, two measured-dead proposers fall back to
  plain, and re-probes prefer the free proposer."""
  from xotorch_support_jetson_tpu.inference.paging import spec_reprobe_proposer, spec_select_proposer

  assert spec_select_proposer("model", {"model": 0.1}, ("model", "ngram"))[0] == "ngram"
  assert spec_select_proposer("model", {"model": 0.1, "ngram": 0.05}, ("model", "ngram"))[0] == "plain"
  assert spec_reprobe_proposer({}, ("ngram", "model")) == "ngram"


def test_committed_r02_artifact_is_filtered():
  """The artifact actually on disk must be neutralized by the filter."""
  path = pathlib.Path(__file__).resolve().parent.parent / "BENCH_r02.json"
  if not path.exists():
    pytest.skip("BENCH_r02.json not present")
  rec = json.load(open(path))
  if "parsed" in rec:
    rec = rec["parsed"]
  v = plausible_value(rec)
  assert v is not None and v < 1000.0, "poisoned r02 headline leaked through the filter"


def test_disagg_gate_keeps_plausible_values():
  """ISSUE 10: the disagg round's emitted numbers (burst TTFT ms, resident
  ITL ratio disagg/colocated, KV-transfer GB/s) ride the same drift-gate
  pattern as gate_kv_tier — generous plausibility bands, custom per field."""
  assert gate_disagg(114.5, lo=0.01, hi=600000.0) == 114.5
  assert gate_disagg(0.85, lo=0.001, hi=1000.0) == 0.85
  assert gate_disagg(1.2, lo=0.001, hi=1000.0) == 1.2  # >1 is reportable, not an artifact
  assert gate_disagg(3.5, lo=1e-6, hi=10000.0) == 3.5


def test_disagg_gate_drops_artifacts():
  assert gate_disagg(None) is None
  assert gate_disagg(0.0) is None  # a zero latency/rate is a broken fixture
  assert gate_disagg(-2.0, lo=0.001, hi=1000.0) is None
  assert gate_disagg(1e9, lo=0.01, hi=600000.0) is None
  assert gate_disagg(2000.0, lo=0.001, hi=1000.0) is None


def test_router_gate_keeps_plausible_values():
  """ISSUE 13: the router round's three fields ride one named gate with
  per-field bounds — the affine/random TTFT ratio (honest values include
  regressions above 1.0, recorded so drift is visible against the < 1.0
  target), the prefix hit rate fraction, and the failover splice window
  (same band as gate_failover: a sub-ms splice means a token raced the
  kill)."""
  from bench import gate_router

  assert gate_router(0.43, lo=0.001, hi=100.0) == 0.43
  assert gate_router(1.3, lo=0.001, hi=100.0) == 1.3  # a regression is a result, not an artifact
  assert gate_router(0.5, lo=0.0, hi=1.0) == 0.5
  assert gate_router(1.0, lo=0.0, hi=1.0) == 1.0  # every routed request affine is legitimate
  assert gate_router(0.0, lo=0.0, hi=1.0) == 0.0  # a dead-affinity round is a result, not an artifact
  assert gate_router(32.6, lo=1.0, hi=120000.0) == 32.6
  assert gate_router(4000.0, lo=1.0, hi=120000.0) == 4000.0


def test_router_gate_drops_artifacts():
  from bench import gate_router

  assert gate_router(None) is None
  assert gate_router(0.0, lo=0.001, hi=100.0) is None  # broken denominator
  assert gate_router(500.0, lo=0.001, hi=100.0) is None
  assert gate_router(1.2, lo=0.0, hi=1.0) is None  # a >1 hit "rate" is a counter bug
  assert gate_router(0.2, lo=1.0, hi=120000.0) is None  # token raced the kill
  assert gate_router(500000.0, lo=1.0, hi=120000.0) is None  # wedged into an outer timeout


def test_mixed_gate_keeps_plausible_values():
  """ISSUE 14: the mixed-tick round's fields ride one named gate with
  per-field bounds — the mid-burst resident ITL means (and amortized
  p50s), their mixed/alternating ratio (honest values include regressions
  above 1.0, recorded so drift is visible against the ≤ 0.5 acceptance
  bar), and the burst TTFT p50s."""
  from bench import gate_mixed

  assert gate_mixed(4.253, lo=0.001, hi=600000.0) == 4.253  # the measured CPU-fixture mean
  assert gate_mixed(0.3956, lo=0.001, hi=1000.0) == 0.3956
  assert gate_mixed(1.2, lo=0.001, hi=1000.0) == 1.2  # a regression is a result, not an artifact
  assert gate_mixed(151.97, lo=0.01, hi=600000.0) == 151.97


def test_mixed_gate_drops_artifacts():
  from bench import gate_mixed

  assert gate_mixed(None) is None
  assert gate_mixed(0.0, lo=0.001, hi=1000.0) is None  # a zero ITL/ratio is a broken fixture
  assert gate_mixed(-1.0, lo=0.001, hi=1000.0) is None
  assert gate_mixed(5e6, lo=0.01, hi=600000.0) is None  # wedged into an outer timeout


def test_paged_b48_gate_keeps_plausible_ratios():
  """ISSUE 11: the paged-vs-dense B=48 ratio rides its own named gate
  (target >= 0.95 with the shape-aware kernel retune). Honest values —
  including regressions below target and modest paged WINS above 1.0 —
  stay recorded so drift is visible against the target."""
  from bench import gate_paged_b48

  assert gate_paged_b48(0.97) == 0.97
  assert gate_paged_b48(1.1) == 1.1
  assert gate_paged_b48(0.80) == 0.80  # the r5 gap: a real number, not an artifact
  assert gate_paged_b48(0.5) == 0.5


def test_paged_b48_gate_drops_artifacts():
  from bench import gate_paged_b48

  assert gate_paged_b48(None) is None
  assert gate_paged_b48(0.0) is None  # broken denominator
  assert gate_paged_b48(-1.0) is None
  assert gate_paged_b48(5.0) is None  # early-return artifact, not a 5x paging win


def test_lora_gate_keeps_plausible_values():
  """ISSUE 15: the multi-LoRA round's drift gate — the mixed-vs-base B=8
  throughput ratio and the swap-in latency ride generous plausibility
  bands; honest regressions (e.g. a ratio below the 0.5 acceptance bar)
  stay RECORDED so the drift is visible in the bench record."""
  assert gate_lora(1.18, lo=0.001, hi=100.0) == 1.18
  assert gate_lora(0.5, lo=0.001, hi=100.0) == 0.5
  assert gate_lora(0.31, lo=0.001, hi=100.0) == 0.31  # below the bar, still recorded
  assert gate_lora(2.05, lo=0.0001, hi=600000.0) == 2.05  # swap ms p50


def test_lora_gate_drops_artifacts():
  assert gate_lora(0.0, lo=0.001, hi=100.0) is None
  assert gate_lora(1e6, lo=0.001, hi=100.0) is None
  assert gate_lora(None) is None


def test_compile_gate_steady_band_is_exactly_zero():
  """ISSUE 19: the program-ledger round's drift gate. The DEFAULT band is
  the steady band [0, 0] — ``steady_state_compiles`` must be exactly zero
  (the no-recompile invariant measured over live dispatches), so any
  nonzero count drops to null and surfaces as a missing metric."""
  from bench import gate_compile

  assert gate_compile(0) == 0.0
  assert gate_compile(0.0) == 0.0
  assert gate_compile(1) is None  # a steady-state recompile happened: broken round
  assert gate_compile(3) is None
  assert gate_compile(-1) is None
  assert gate_compile(None) is None


def test_compile_gate_warmup_band_keeps_plausible_seconds():
  """``warmup_compile_s_total`` rides the same gate with a generous
  plausibility band; 0.0 is legal (XOT_TPU_PROGRAMS=0 disables the ledger
  without nulling the bench key)."""
  from bench import gate_compile

  assert gate_compile(0.0, lo=0.0, hi=3600.0) == 0.0
  assert gate_compile(0.8421, lo=0.0, hi=3600.0) == 0.8421
  assert gate_compile(120.0, lo=0.0, hi=3600.0) == 120.0
  assert gate_compile(7200.0, lo=0.0, hi=3600.0) is None  # wedged into an outer timeout
  assert gate_compile(None, lo=0.0, hi=3600.0) is None
