"""/v1/image/generations end-to-end: the reference exposes this surface over
dead code (its SD registry entry is commented out, reference models.py:167-168;
handler at chatgpt_api.py:445-535); here the JAX diffusion pipeline actually
serves it. Covers: progress-line streaming + saved-PNG URL, img2img via
base64 image_url, 501 on engines without image support, 400 on non-SD models.
"""

import base64
import io
import json

import jax
import pytest
from aiohttp.test_utils import TestClient, TestServer

from xotorch_support_jetson_tpu.api.chatgpt_api import ChatGPTAPI
from xotorch_support_jetson_tpu.inference.dummy_engine import DummyInferenceEngine
from xotorch_support_jetson_tpu.inference.jax_engine import JaxShardedInferenceEngine
from xotorch_support_jetson_tpu.inference.shard import Shard
from xotorch_support_jetson_tpu.models.diffusion import tiny_diffusion_config
from xotorch_support_jetson_tpu.models.diffusion_loader import init_diffusion_params
from xotorch_support_jetson_tpu.orchestration.node import Node
from xotorch_support_jetson_tpu.topology.partitioning import RingMemoryWeightedPartitioningStrategy
from tests_support_stubs import NoDiscovery, StubServer

MODEL = "stable-diffusion-2-1-base"


async def _make_api(engine):
  node = Node(
    "img-node", StubServer(), engine, NoDiscovery(), None,
    RingMemoryWeightedPartitioningStrategy(),
  )
  await node.start()
  api = ChatGPTAPI(node, type(engine).__name__, response_timeout=60, default_model="dummy")
  client = TestClient(TestServer(api.app))
  await client.start_server()
  return node, api, client


def _jax_engine_with_tiny_sd():
  engine = JaxShardedInferenceEngine(use_local_mesh=False)
  cfg = tiny_diffusion_config()
  params = init_diffusion_params(jax.random.PRNGKey(0), cfg)
  full = Shard(MODEL, 0, 30, 31)  # registry card depth (vestigial for SD)
  engine.load_test_diffusion(full, cfg, params)
  return engine


async def _read_lines(resp):
  lines = []
  async for chunk in resp.content:
    chunk = chunk.strip()
    if chunk:
      lines.append(json.loads(chunk))
  return lines


@pytest.mark.asyncio
async def test_image_generation_streams_progress_and_url():
  node, api, client = await _make_api(_jax_engine_with_tiny_sd())
  try:
    resp = await client.post("/v1/image/generations", json={"model": MODEL, "prompt": "a red cube", "steps": 6, "seed": 3})
    assert resp.status == 200
    lines = await _read_lines(resp)

    progress = [l for l in lines if "progress" in l]
    assert progress, lines
    assert progress[0]["step"] == 0 and progress[-1]["step"] == progress[-1]["total_steps"] == 6
    assert "Progress: [" in progress[-1]["progress"]

    final = [l for l in lines if "images" in l]
    assert len(final) == 1
    url = final[0]["images"][0]["url"]
    assert final[0]["images"][0]["content_type"] == "image/png"

    # the URL must serve a real PNG of the pipeline's output size
    png = await client.get(url[url.index("/images/"):])
    assert png.status == 200
    from PIL import Image

    img = Image.open(io.BytesIO(await png.read()))
    assert img.size == (16, 16)

    # deterministic per seed: same request → same bytes
    resp2 = await client.post("/v1/image/generations", json={"model": MODEL, "prompt": "a red cube", "steps": 6, "seed": 3})
    lines2 = await _read_lines(resp2)
    url2 = [l for l in lines2 if "images" in l][0]["images"][0]["url"]
    png2 = await client.get(url2[url2.index("/images/"):])
    assert await png2.read() == await (await client.get(url[url.index("/images/"):])).read()
  finally:
    await client.close()
    await node.stop()


@pytest.mark.asyncio
async def test_image_generation_img2img():
  node, api, client = await _make_api(_jax_engine_with_tiny_sd())
  try:
    from PIL import Image

    buf = io.BytesIO()
    Image.new("RGB", (16, 16), (200, 30, 30)).save(buf, format="PNG")
    data_url = "data:image/png;base64," + base64.b64encode(buf.getvalue()).decode()

    resp = await client.post(
      "/v1/image/generations",
      json={"model": MODEL, "prompt": "bluer", "steps": 4, "image_url": data_url, "strength": 0.5},
    )
    assert resp.status == 200
    lines = await _read_lines(resp)
    final = [l for l in lines if "images" in l]
    assert len(final) == 1
    # img2img runs strength*steps denoise steps
    progress = [l for l in lines if "progress" in l]
    assert progress[-1]["total_steps"] == 2
  finally:
    await client.close()
    await node.stop()


@pytest.mark.asyncio
async def test_image_generation_rejects_non_sd_model_and_dummy_engine():
  node, api, client = await _make_api(DummyInferenceEngine())
  try:
    resp = await client.post("/v1/image/generations", json={"model": "llama-3.2-1b", "prompt": "x"})
    assert resp.status == 400
    resp = await client.post("/v1/image/generations", json={"model": MODEL, "prompt": "x"})
    assert resp.status == 501  # engine cannot generate images (reference-parity refusal)
  finally:
    await client.close()
    await node.stop()


@pytest.mark.asyncio
async def test_image_generation_bad_params_are_400():
  node, api, client = await _make_api(_jax_engine_with_tiny_sd())
  try:
    for bad in ({"steps": "thirty"}, {"size": 512}, {"seed": None}, {"steps": 0}, {"size": [512]}):
      resp = await client.post("/v1/image/generations", json={"model": MODEL, "prompt": "x", **bad})
      assert resp.status == 400, bad
  finally:
    await client.close()
    await node.stop()


@pytest.mark.asyncio
async def test_image_generation_bad_image_url_is_400():
  node, api, client = await _make_api(_jax_engine_with_tiny_sd())
  try:
    resp = await client.post("/v1/image/generations", json={"model": MODEL, "prompt": "x", "image_url": "data:image/png;base64,!!!notb64"})
    assert resp.status == 400
  finally:
    await client.close()
    await node.stop()


@pytest.mark.asyncio
async def test_openai_images_alias_url_and_b64():
  """/v1/images/generations (plural) — the OpenAI Images API shape the
  reference never had: blocking {created, data:[{url}|{b64_json}]}."""
  node, api, client = await _make_api(_jax_engine_with_tiny_sd())
  try:
    resp = await client.post("/v1/images/generations", json={"prompt": "a cube", "n": 2, "steps": 4})
    assert resp.status == 200
    body = await resp.json()
    assert "created" in body and len(body["data"]) == 2
    for entry in body["data"]:
      png = await client.get(entry["url"][entry["url"].index("/images/"):])
      assert png.status == 200

    resp = await client.post("/v1/images/generations", json={"prompt": "a cube", "response_format": "b64_json", "steps": 3})
    body = await resp.json()
    import base64 as b64mod

    from PIL import Image

    raw = b64mod.b64decode(body["data"][0]["b64_json"])
    img = Image.open(io.BytesIO(raw))
    assert img.size == (16, 16)

    # OpenAI-style size string parses; bad values are clean 400s
    resp = await client.post("/v1/images/generations", json={"prompt": "x", "size": "16x16", "steps": 2})
    assert resp.status == 200
    for bad in ({"n": 9}, {"size": "0x16"}, {"response_format": "gif"}):
      resp = await client.post("/v1/images/generations", json={"prompt": "x", **bad})
      assert resp.status == 400, bad
  finally:
    await client.close()
    await node.stop()


@pytest.mark.asyncio
async def test_streaming_route_n_images():
  """n>1 on the reference-shaped streaming route: one denoise batch, n URLs."""
  node, api, client = await _make_api(_jax_engine_with_tiny_sd())
  try:
    resp = await client.post("/v1/image/generations", json={"model": MODEL, "prompt": "cubes", "steps": 4, "n": 3, "seed": 5})
    assert resp.status == 200
    lines = await _read_lines(resp)
    final = [l for l in lines if "images" in l]
    assert len(final) == 1 and len(final[0]["images"]) == 3
    urls = {img["url"] for img in final[0]["images"]}
    assert len(urls) == 3  # distinct files
  finally:
    await client.close()
    await node.stop()
