"""Partition math tests — mirrors the reference's coverage-guarantee strategy
(``topology/test_map_partitions.py``, ``test_ring_memory_weighted_...py``)."""

from xotorch_support_jetson_tpu.inference.shard import Shard
from xotorch_support_jetson_tpu.topology import (
  DeviceCapabilities,
  DeviceFlops,
  Partition,
  RingMemoryWeightedPartitioningStrategy,
  Topology,
  map_partitions_to_shards,
)


def caps(memory: int) -> DeviceCapabilities:
  return DeviceCapabilities(model="m", chip="c", memory=memory, flops=DeviceFlops(0, 0, 0))


def assert_full_coverage(shards: list[Shard], n_layers: int):
  assert shards[0].start_layer == 0
  assert shards[-1].end_layer == n_layers - 1
  for a, b in zip(shards, shards[1:]):
    assert b.start_layer == a.end_layer + 1


def test_map_partitions_exact_thirds():
  partitions = [Partition("a", 0.0, 1 / 3), Partition("b", 1 / 3, 2 / 3), Partition("c", 2 / 3, 1.0)]
  shards = map_partitions_to_shards(partitions, 32, "m")
  assert [(s.start_layer, s.end_layer) for s in shards] == [(0, 10), (11, 20), (21, 31)]
  assert_full_coverage(shards, 32)


def test_map_partitions_rounding_coverage():
  # Fractions that don't sum exactly to 1.0 must still cover all layers.
  partitions = [Partition("a", 0.0, 0.42857), Partition("b", 0.42857, 0.71428), Partition("c", 0.71428, 0.99999)]
  for n_layers in (5, 7, 16, 27, 32, 80, 126):
    shards = map_partitions_to_shards(partitions, n_layers, "m")
    assert_full_coverage(shards, n_layers)


def test_map_partitions_single_node():
  shards = map_partitions_to_shards([Partition("a", 0.0, 1.0)], 16, "m")
  assert shards == [Shard("m", 0, 15, 16)]


def test_map_partitions_more_nodes_than_layers():
  partitions = [Partition(str(i), i / 8, (i + 1) / 8) for i in range(8)]
  shards = map_partitions_to_shards(partitions, 4, "m")
  # Fewer shards than partitions is fine; coverage must hold.
  assert_full_coverage(shards, 4)


def test_ring_memory_weighted_proportional():
  t = Topology()
  t.update_node("node1", caps(16 * 1024))
  t.update_node("node2", caps(48 * 1024))
  partitions = RingMemoryWeightedPartitioningStrategy().partition(t)
  # Sorted by memory desc: node2 gets 75%, node1 gets 25%.
  assert partitions[0].node_id == "node2"
  assert abs(partitions[0].end - 0.75) < 1e-4
  assert abs(partitions[-1].end - 1.0) < 1e-4


def test_ring_memory_weighted_deterministic_tiebreak():
  t1, t2 = Topology(), Topology()
  for t in (t1, t2):
    for nid in ("b", "a", "c"):
      t.update_node(nid, caps(1024))
  p1 = RingMemoryWeightedPartitioningStrategy().partition(t1)
  p2 = RingMemoryWeightedPartitioningStrategy().partition(t2)
  assert [p.node_id for p in p1] == [p.node_id for p in p2] == ["c", "b", "a"]


def test_ring_memory_weighted_zero_memory_equal_split():
  t = Topology()
  for nid in ("a", "b"):
    t.update_node(nid, caps(0))
  partitions = RingMemoryWeightedPartitioningStrategy().partition(t)
  assert abs(partitions[0].end - 0.5) < 1e-9
  assert abs(partitions[1].end - 1.0) < 1e-9


def test_shard_properties():
  s = Shard("m", 0, 15, 32)
  assert s.is_first_layer and not s.is_last_layer
  assert s.n_shard_layers == 16
  assert s.overlaps(Shard("m", 15, 20, 32))
  assert not s.overlaps(Shard("m", 16, 31, 32))
  assert not s.overlaps(Shard("other", 0, 15, 32))
  assert Shard.from_dict(s.to_dict()) == s


def test_topology_merge():
  t1, t2 = Topology(), Topology()
  t1.update_node("a", caps(1))
  t2.update_node("b", caps(2))
  t2.add_edge("b", "c")
  t1.merge("b", t2)
  assert set(t1.nodes) == {"a", "b"}
  assert t1.get_neighbors("b") == {"c"}
  rt = Topology.from_json(t1.to_json())
  assert set(rt.nodes) == {"a", "b"}
  assert rt.get_neighbors("b") == {"c"}
