"""UDP discovery over real loopback sockets (reference test strategy:
``networking/udp/test_udp_discovery.py`` — crossed listen/broadcast ports,
real gRPC servers, mocked compute)."""

import asyncio

import pytest

from xotorch_support_jetson_tpu.inference.dummy_engine import DummyInferenceEngine
from xotorch_support_jetson_tpu.networking.grpc.grpc_peer_handle import GRPCPeerHandle
from xotorch_support_jetson_tpu.networking.grpc.grpc_server import GRPCServer
from xotorch_support_jetson_tpu.networking.udp.udp_discovery import UDPDiscovery
from xotorch_support_jetson_tpu.orchestration.node import Node
from xotorch_support_jetson_tpu.topology.device_capabilities import DeviceCapabilities, DeviceFlops
from xotorch_support_jetson_tpu.topology.partitioning import RingMemoryWeightedPartitioningStrategy
from xotorch_support_jetson_tpu.utils.helpers import find_available_port
from tests_support_stubs import NoDiscovery, StubServer

CAPS = DeviceCapabilities(model="test", chip="cpu", memory=2048, flops=DeviceFlops(1, 2, 4))


async def _grpc_backed_node(port):
  node = Node("udp-target", StubServer(), DummyInferenceEngine(), NoDiscovery(), None, RingMemoryWeightedPartitioningStrategy())
  # Bind all interfaces: the UDP beacon's source address is the host's
  # outbound interface, and the adopting side health-checks that address.
  server = GRPCServer(node, "0.0.0.0", port)
  node.server = server
  await node.start()
  return node


@pytest.mark.asyncio
async def test_udp_discovery_two_instances_discover_each_other():
  # Crossed ports: A broadcasts on B's listen port and vice versa.
  port_a, port_b = find_available_port(), find_available_port()
  grpc_a, grpc_b = find_available_port("127.0.0.1"), find_available_port("127.0.0.1")
  node_b = await _grpc_backed_node(grpc_b)

  seen = {}

  def make_handle(pid, addr, desc, caps):
    handle = GRPCPeerHandle(pid, addr, desc, caps)
    seen[pid] = addr
    return handle

  disc_a = UDPDiscovery("node-a", grpc_a, listen_port=port_a, broadcast_port=port_b, create_peer_handle=make_handle, broadcast_interval=0.2, device_capabilities=CAPS)
  disc_b = UDPDiscovery("node-b", grpc_b, listen_port=port_b, broadcast_port=port_a, create_peer_handle=lambda *a: GRPCPeerHandle(*a), broadcast_interval=0.2, device_capabilities=CAPS)
  # a listens where b broadcasts: a should adopt b (health-checked via b's real gRPC).
  await disc_b.start()
  await disc_a.start()
  try:
    peers = []
    for _ in range(100):
      peers = await disc_a.discover_peers()
      if peers:
        break
      await asyncio.sleep(0.1)
    assert peers and peers[0].id() == "node-b"
    assert peers[0].device_capabilities().memory == 2048
  finally:
    await disc_a.stop()
    await disc_b.stop()
    await node_b.stop()


@pytest.mark.asyncio
async def test_udp_discovery_evicts_dead_peer(monkeypatch):
  import xotorch_support_jetson_tpu.networking.grpc.grpc_peer_handle as gph

  monkeypatch.setattr(gph, "CONNECT_TIMEOUT", 1.0)
  monkeypatch.setattr(gph, "HEALTH_TIMEOUT", 1.0)
  port_a, port_b = find_available_port(), find_available_port()
  grpc_b = find_available_port("127.0.0.1")
  node_b = await _grpc_backed_node(grpc_b)
  disc_a = UDPDiscovery(
    "node-a", 1, listen_port=port_a, broadcast_port=port_b,
    create_peer_handle=lambda *a: GRPCPeerHandle(*a),
    broadcast_interval=0.2, discovery_timeout=600, device_capabilities=CAPS,
  )
  disc_b = UDPDiscovery("node-b", grpc_b, listen_port=port_b, broadcast_port=port_a, create_peer_handle=lambda *a: GRPCPeerHandle(*a), broadcast_interval=0.2, device_capabilities=CAPS)
  await disc_b.start()
  await disc_a.start()
  try:
    for _ in range(100):
      if await disc_a.discover_peers():
        break
      await asyncio.sleep(0.1)
    assert await disc_a.discover_peers()

    # Kill node-b's gRPC server AND its beacons: health checks fail → eviction.
    await disc_b.stop()
    await node_b.stop()
    node_b = None
    for _ in range(100):
      if not await disc_a.discover_peers():
        break
      await asyncio.sleep(0.1)
    assert await disc_a.discover_peers() == []
  finally:
    await disc_a.stop()
    if node_b is not None:
      await node_b.stop()


@pytest.mark.asyncio
async def test_udp_discovery_filters_disallowed_node_ids():
  port_a, port_b = find_available_port(), find_available_port()
  disc_a = UDPDiscovery(
    "node-a", 1, listen_port=port_a, broadcast_port=port_b,
    create_peer_handle=lambda *a: GRPCPeerHandle(*a),
    broadcast_interval=0.2, device_capabilities=CAPS,
    allowed_node_ids=["only-this-one"],
  )
  disc_b = UDPDiscovery("node-b", 2, listen_port=port_b, broadcast_port=port_a, create_peer_handle=lambda *a: GRPCPeerHandle(*a), broadcast_interval=0.2, device_capabilities=CAPS)
  await disc_b.start()
  await disc_a.start()
  try:
    await asyncio.sleep(1.0)
    assert await disc_a.discover_peers() == []
  finally:
    await disc_a.stop()
    await disc_b.stop()
