"""Test harness config: force an 8-device virtual CPU mesh.

Multi-chip TPU hardware is unavailable in CI; all sharding/pipeline tests run
against 8 virtual CPU devices (the same validation path the driver uses via
``__graft_entry__.dryrun_multichip``). Must run before jax is imported
anywhere, hence the env mutation at module import time.
"""

import asyncio
import inspect
import os

import pytest

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
  os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()
os.environ.setdefault("XOT_TPU_UUID", "test-node-id")
os.environ.setdefault("HF_HUB_OFFLINE", "1")  # no egress in CI; fail fast
# Incident auto-captures (ISSUE 9: stall watchdog / anomaly watchers inside
# cluster tests) must never write into the real $XOT_HOME from CI.
os.environ.setdefault("XOT_TPU_BUNDLE_DIR", "/tmp/xot-test-bundles")
# The n-gram proposer (ISSUE 12) makes XOT_TPU_SPEC_BATCH=auto speculate
# DRAFT-FREE — the production default. In the suite that would flip every
# batched greedy test onto the spec programs (one extra compiled program
# per module for streams that are already identity-pinned), so the suite
# pins the family OFF here; tests/test_spec_ngram.py turns it on explicitly
# and pins the draft-free behavior end to end.
os.environ.setdefault("XOT_TPU_SPEC_NGRAM", "0")

# The axon TPU plugin in this image overrides JAX_PLATFORMS at import time;
# the config API still wins, so force the CPU backend explicitly.
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")


def pytest_configure(config):
  config.addinivalue_line("markers", "asyncio: run test in an asyncio event loop")


@pytest.fixture(autouse=True)
def _fp32_matmuls():
  """Numerical tests compare reduction orders; run matmuls in true fp32.

  (This build's DEFAULT matmul precision computes fp32 matmuls with bf16
  passes, which would swamp cache-vs-full equivalence at ~2^-8.)
  """
  import jax

  with jax.default_matmul_precision("highest"):
    yield


@pytest.hookimpl(tryfirst=True)
def pytest_pyfunc_call(pyfuncitem):
  """Minimal pytest-asyncio replacement (the plugin isn't in the image)."""
  fn = pyfuncitem.obj
  if inspect.iscoroutinefunction(fn):
    kwargs = {name: pyfuncitem.funcargs[name] for name in pyfuncitem._fixtureinfo.argnames}
    asyncio.run(fn(**kwargs))
    return True
  return None


@pytest.fixture(autouse=True, scope="module")
def _clear_jax_caches_between_modules():
  """Drop compiled executables between test modules.

  ~270 tests in one process accumulate hundreds of live XLA CPU executables;
  full-suite runs (and only full-suite runs — every module passes in
  isolation) intermittently segfault inside backend_compile_and_load under
  that load. Executables are rarely shared across modules (each uses its own
  tiny configs), so clearing costs little and keeps the native state small.
  """
  yield
  import jax

  jax.clear_caches()
