"""API correctness: truthful usage, stop strings, error mapping, per-request
top_k, queue limits, longrope default cap (round-2 VERDICT/ADVICE items)."""

import asyncio
import json

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from aiohttp.test_utils import TestClient, TestServer

from xotorch_support_jetson_tpu.api.chatgpt_api import ChatGPTAPI, find_stop
from xotorch_support_jetson_tpu.inference.dummy_engine import DummyInferenceEngine
from xotorch_support_jetson_tpu.inference.engine import PromptTooLongError, ServerOverloadedError
from xotorch_support_jetson_tpu.orchestration.node import Node
from xotorch_support_jetson_tpu.topology.partitioning import RingMemoryWeightedPartitioningStrategy
from tests_support_stubs import NoDiscovery, StubServer


async def _make_api(**api_kwargs):
  node = Node(
    "api-node",
    StubServer(),
    DummyInferenceEngine(),
    NoDiscovery(),
    None,
    RingMemoryWeightedPartitioningStrategy(),
    max_generate_tokens=50,
  )
  await node.start()
  api = ChatGPTAPI(node, "DummyInferenceEngine", response_timeout=30, default_model="dummy", **api_kwargs)
  client = TestClient(TestServer(api.app))
  await client.start_server()
  return node, api, client


def test_find_stop_helper():
  assert find_stop("hello world", ("wor",)) == (6, 6)
  # no match, but a suffix could start a stop string -> held back
  cut, safe = find_stop("hello wo", ("world",))
  assert cut is None and safe == 6
  cut, safe = find_stop("hello", ("xyz",))
  assert cut is None and safe == 5
  assert find_stop("abab", ("ab",)) == (0, 0)


@pytest.mark.asyncio
async def test_blocking_usage_and_stop_string():
  node, api, client = await _make_api()
  try:
    # Dummy engine: prompt "aaaa" -> token [4], then 5, 6, 7, ... greedy.
    resp = await client.post(
      "/v1/chat/completions",
      json={"model": "dummy", "messages": [{"role": "user", "content": "aaaa"}], "stream": False, "stop": "8"},
    )
    assert resp.status == 200, await resp.text()
    data = await resp.json()
    choice = data["choices"][0]
    assert choice["finish_reason"] == "stop"
    assert "8" not in choice["message"]["content"]
    assert "7" in choice["message"]["content"]
    usage = data["usage"]
    assert usage["prompt_tokens"] == 1  # "aaaa" -> one 4-char word
    assert usage["completion_tokens"] >= 1
    assert usage["total_tokens"] == usage["prompt_tokens"] + usage["completion_tokens"]
  finally:
    await client.close()
    await node.stop()


@pytest.mark.asyncio
async def test_streaming_stop_string_and_include_usage():
  node, api, client = await _make_api()
  try:
    resp = await client.post(
      "/v1/chat/completions",
      json={
        "model": "dummy",
        "messages": [{"role": "user", "content": "aaaa"}],
        "stream": True,
        "stop": ["8"],
        "stream_options": {"include_usage": True},
      },
    )
    assert resp.status == 200
    body = (await resp.read()).decode()
    events = [json.loads(line[6:]) for line in body.splitlines() if line.startswith("data: ") and line != "data: [DONE]"]
    text = "".join(e["choices"][0]["delta"].get("content", "") for e in events if e.get("choices"))
    assert "8" not in text and "7" in text
    finishes = [e["choices"][0].get("finish_reason") for e in events if e.get("choices")]
    assert "stop" in finishes
    usage_events = [e for e in events if "usage" in e]
    assert usage_events and usage_events[-1]["usage"]["prompt_tokens"] == 1
    assert body.rstrip().endswith("data: [DONE]")
  finally:
    await client.close()
    await node.stop()


@pytest.mark.asyncio
async def test_prompt_too_long_maps_to_400_and_overload_to_429():
  node, api, client = await _make_api()
  try:
    orig = node.process_prompt

    async def raise_too_long(*a, **k):
      raise PromptTooLongError("prompt of 9999 tokens exceeds the 128-token context window")

    node.process_prompt = raise_too_long
    resp = await client.post(
      "/v1/chat/completions", json={"model": "dummy", "messages": [{"role": "user", "content": "x"}], "stream": False}
    )
    assert resp.status == 400
    err = (await resp.json())["error"]
    assert err["code"] == "context_length_exceeded"

    async def raise_overload(*a, **k):
      raise ServerOverloadedError("request queue full (64 waiting)")

    node.process_prompt = raise_overload
    resp = await client.post(
      "/v1/chat/completions", json={"model": "dummy", "messages": [{"role": "user", "content": "x"}], "stream": False}
    )
    assert resp.status == 429
    node.process_prompt = orig
  finally:
    await client.close()
    await node.stop()


@pytest.mark.asyncio
async def test_streaming_error_before_first_token_gets_real_status():
  """Failures knowable before the first token must surface as proper HTTP
  statuses, not a 200 SSE stream (the stream is committed only after the
  first token batch arrives)."""
  node, api, client = await _make_api()
  try:

    async def boom(*a, **k):
      raise PromptTooLongError("prompt of 9999 tokens exceeds the 128-token context window")

    node.process_prompt = boom
    resp = await client.post(
      "/v1/chat/completions", json={"model": "dummy", "messages": [{"role": "user", "content": "x"}], "stream": True}
    )
    assert resp.status == 400
    assert (await resp.json())["error"]["code"] == "context_length_exceeded"
  finally:
    await client.close()
    await node.stop()


@pytest.mark.asyncio
async def test_streaming_error_after_first_token_reported_in_band():
  """After prepare(), failures must arrive as SSE events, not a second
  response object (ADVICE round-1 item 1)."""
  node, api, client = await _make_api()
  try:

    async def boom_after_token(shard, prompt, request_id, inference_state=None, **k):
      node.trigger_on_token_callbacks(request_id, [5], False)
      await asyncio.sleep(0.05)
      raise RuntimeError("engine exploded")

    node.process_prompt = boom_after_token
    resp = await client.post(
      "/v1/chat/completions", json={"model": "dummy", "messages": [{"role": "user", "content": "x"}], "stream": True}
    )
    assert resp.status == 200  # stream already committed by the first token
    body = (await resp.read()).decode()
    assert "engine exploded" in body
    assert body.rstrip().endswith("data: [DONE]")
  finally:
    await client.close()
    await node.stop()


@pytest.mark.asyncio
async def test_streaming_flushes_heldback_stop_prefix_on_finish():
  """Text held back as a potential stop-string prefix must flush when
  generation finishes without the stop string completing."""
  node, api, client = await _make_api()
  try:
    # Dummy tokens run 5..54 (max 50): text ends "... 53 54"; "4X" holds back
    # the trailing "4" until EOS-less finish, which must flush it.
    resp = await client.post(
      "/v1/chat/completions",
      json={"model": "dummy", "messages": [{"role": "user", "content": "aaaa"}], "stream": True, "stop": ["4X"]},
    )
    body = (await resp.read()).decode()
    events = [json.loads(line[6:]) for line in body.splitlines() if line.startswith("data: ") and line != "data: [DONE]"]
    text = "".join(e["choices"][0]["delta"].get("content", "") for e in events if e.get("choices"))

    resp2 = await client.post(
      "/v1/chat/completions",
      json={"model": "dummy", "messages": [{"role": "user", "content": "aaaa"}], "stream": False},
    )
    blocking_text = (await resp2.json())["choices"][0]["message"]["content"]
    assert text == blocking_text  # no silent truncation of the held suffix
  finally:
    await client.close()
    await node.stop()


def test_solo_engine_rejects_too_long_prompt():
  from xotorch_support_jetson_tpu.inference.jax_engine import JaxShardedInferenceEngine
  from xotorch_support_jetson_tpu.models.config import tiny_test_config
  from xotorch_support_jetson_tpu.models.decoder import full_model_params

  cfg = tiny_test_config(n_layers=2, max_seq_len=32)
  params, shard = full_model_params(jax.random.PRNGKey(0), cfg, "m")
  eng = JaxShardedInferenceEngine(use_local_mesh=False)
  eng.load_test_model(shard, cfg, params)
  with pytest.raises(PromptTooLongError):
    eng._infer_tensor_sync("r", shard, np.ones((1, 40), np.int32), None)
  assert "r" not in eng.sessions


def test_batched_scheduler_prompt_too_long_and_queue_limit():
  from xotorch_support_jetson_tpu.inference.batch_scheduler import BatchedServer
  from xotorch_support_jetson_tpu.inference.jax_engine import JaxShardedInferenceEngine
  from xotorch_support_jetson_tpu.models.config import tiny_test_config
  from xotorch_support_jetson_tpu.models.decoder import full_model_params

  cfg = tiny_test_config(n_layers=2, max_seq_len=64)
  params, shard = full_model_params(jax.random.PRNGKey(0), cfg, "m")
  eng = JaxShardedInferenceEngine(use_local_mesh=False, max_seq_len=64)
  eng.load_test_model(shard, cfg, params)

  async def run():
    server = BatchedServer(eng, n_slots=2, chunk=4, max_queue=1)

    def emit(rid, toks, fin):
      pass

    with pytest.raises(PromptTooLongError):
      await server.submit("too-long", np.ones(70, np.int32), max_tokens=4, temp=0.0, top_k=35, eos_ids=(), emit=emit)

    # Saturate the queue while the loop is blocked admitting; the next submit
    # must fail fast with ServerOverloadedError.
    server2 = BatchedServer(eng, n_slots=2, chunk=4, max_queue=0)
    with pytest.raises(ServerOverloadedError):
      await server2.submit("r1", np.ones(4, np.int32), max_tokens=2, temp=0.0, top_k=35, eos_ids=(), emit=emit)
    server.shutdown()
    server2.shutdown()

  asyncio.run(run())


def test_per_request_top_k_is_honored_per_row():
  """top_k=1 with temp>0 must equal greedy for that row while other rows
  sample from their own k (was: pool-wide static top_k, NOTES round-1)."""
  from xotorch_support_jetson_tpu.models.config import tiny_test_config
  from xotorch_support_jetson_tpu.models.decoder import full_model_params, fused_batch_decode, init_kv_cache

  cfg = tiny_test_config(n_layers=2, max_seq_len=64)
  params, shard = full_model_params(jax.random.PRNGKey(1), cfg, "m")
  B, n_steps = 3, 6
  prompt_len = 4

  def run(top_ks, temps):
    cache = init_kv_cache(cfg, shard.n_shard_layers, B, 64)
    from xotorch_support_jetson_tpu.models.decoder import prefill_into_slot
    import jax.numpy as jnp

    for row in range(B):
      _, cache = prefill_into_slot(params, cfg, shard, jnp.ones((1, prompt_len), jnp.int32), cache, jnp.int32(row), jnp.int32(prompt_len))
    toks, _, _, _ = fused_batch_decode(
      params, cfg, shard,
      jnp.full((B, 1), 7, jnp.int32), cache, jnp.full((B,), prompt_len, jnp.int32),
      jnp.ones((B,), bool), jnp.asarray(temps, jnp.float32), n_steps,
      top_k=jnp.asarray(top_ks, jnp.int32), key=jax.random.PRNGKey(9),
    )
    return np.asarray(toks)

  greedy_rows = run([1, 1, 1], [0.0, 0.0, 0.0])
  mixed = run([1, 1, 50], [0.9, 0.0, 0.9])  # row0 temp>0 but k=1 => greedy; row1 greedy; row2 samples
  np.testing.assert_array_equal(mixed[0], greedy_rows[0])
  np.testing.assert_array_equal(mixed[1], greedy_rows[1])


def test_longrope_default_cap_and_explicit_override():
  from xotorch_support_jetson_tpu.inference.jax_engine import JaxShardedInferenceEngine
  from xotorch_support_jetson_tpu.models.config import LongRopeScaling, tiny_test_config

  scaling = LongRopeScaling(
    short_factor=(1.0,) * 8,
    long_factor=(4.0,) * 8,
    original_max_position_embeddings=2048,
    attention_factor=1.0,
  )
  cfg = tiny_test_config(head_dim=16, max_seq_len=16384, rope_scaling=scaling)

  eng_default = JaxShardedInferenceEngine(use_local_mesh=False)  # cap defaulted
  assert eng_default._serving_cap(cfg) == 2048

  eng_explicit = JaxShardedInferenceEngine(use_local_mesh=False, max_seq_len=8192)
  assert eng_explicit._serving_cap(cfg) == 8192

  plain = tiny_test_config(max_seq_len=16384)
  assert eng_default._serving_cap(plain) == min(eng_default.max_seq_len, 16384)
