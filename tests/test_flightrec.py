"""Flight recorder, anomaly watchers, and incident bundles (ISSUE 9).

Covers: the wide-event ring (bounds, seq, causal-order query filters), the
off switch (XOT_TPU_FLIGHTREC=0 records NOTHING — the byte-identical-off
contract), the tracer stage choke-point bridge, breaker/health transition
hooks, every anomaly rule with its cooldown, local bundle assembly, the
auto-capture rate limit, and the /v1/events + /v1/debug/bundle endpoints.
"""

import asyncio
import json
import time

import pytest
from aiohttp.test_utils import TestClient, TestServer

from xotorch_support_jetson_tpu.networking.retry import CircuitBreaker, PeerHealth, breakers, peer_health
from xotorch_support_jetson_tpu.orchestration.flightrec import (
  AnomalyWatchers,
  BundleManager,
  FlightRecorder,
  assemble_local_bundle,
  bundles,
  flightrec,
)
from xotorch_support_jetson_tpu.orchestration.tracing import Tracer, tracer
from xotorch_support_jetson_tpu.utils.metrics import metrics as gm


@pytest.fixture(autouse=True)
def _clean_state():
  flightrec.clear()
  bundles.reset()
  breakers.reset()
  peer_health.reset()
  yield
  flightrec.clear()
  bundles.reset()
  breakers.reset()
  peer_health.reset()


# ------------------------------------------------------------------- the ring


def test_ring_bounds_seq_and_query_filters():
  rec = FlightRecorder(capacity=64)
  for i in range(80):
    rec.record("admitted" if i % 2 == 0 else "shed", request_id=f"r{i}", peer=f"p{i % 3}")
  assert len(rec) == 64  # bounded: oldest 16 rotated out
  assert rec.last_seq() == 80
  events = rec.recent(1000)
  assert [e["seq"] for e in events] == list(range(17, 81))  # causal order, oldest-first
  # Type filter.
  sheds = rec.query(types={"shed"}, limit=1000)
  assert sheds and all(e["type"] == "shed" for e in sheds)
  # Request filter.
  assert [e["request_id"] for e in rec.query(request_id="r50")] == ["r50"]
  # Peer filter + newest-N cap keeps the TAIL.
  p0 = rec.query(peer="p0", limit=3)
  assert len(p0) == 3 and p0[-1]["seq"] == 79  # i=78 is p0; seq = i+1
  # min_seq filter.
  assert all(e["seq"] >= 75 for e in rec.query(min_seq=75, limit=1000))
  # since_s: everything is fresh, a 0-second window excludes all.
  assert rec.query(since_s=0.0) == []
  assert len(rec.query(since_s=3600.0, limit=1000)) == 64


def test_disabled_records_nothing(monkeypatch):
  """XOT_TPU_FLIGHTREC=0: record() returns before touching the ring — the
  repo's byte-identical-off pattern."""
  monkeypatch.setenv("XOT_TPU_FLIGHTREC", "0")
  rec = FlightRecorder(capacity=16)
  assert rec.enabled is False
  assert rec.record("admitted", request_id="r1") is None
  assert len(rec) == 0
  monkeypatch.delenv("XOT_TPU_FLIGHTREC")
  assert rec.enabled is True
  assert rec.record("admitted", request_id="r1") is not None


def test_events_count_into_metrics():
  before = gm.counter_value("flightrec_events_total", labels={"type": "parked"})
  flightrec.record("parked", request_id="r-m")
  assert gm.counter_value("flightrec_events_total", labels={"type": "parked"}) == before + 1


# ------------------------------------------------------- tracer stage bridge


def test_stage_choke_point_forwards_consequential_stages():
  t = Tracer()
  seq0 = flightrec.last_seq()
  t.stage("r-b", "queued")  # traffic, not a transition: NOT recorded
  t.stage("r-b", "admitted", {"class": "interactive"})
  t.stage("r-b", "preempted", {"row": 1})
  t.stage("r-b", "shed", {"reason": "overload", "class": "batch"}, terminal=True)
  evs = flightrec.query(request_id="r-b", min_seq=seq0 + 1, limit=100)
  assert [e["type"] for e in evs] == ["admitted", "preempted", "shed"]
  assert evs[2]["cause"] == "overload"
  # The terminal refusal fed SLO availability via the same hook.
  assert gm.counter_value("slo_requests_bad_total", labels={"class": "batch", "reason": "shed"}) >= 1


def test_end_request_records_complete_event():
  t = Tracer()
  t.stage("r-c", "queued")
  seq0 = flightrec.last_seq()
  t.end_request("r-c")
  evs = flightrec.query(request_id="r-c", min_seq=seq0 + 1)
  assert [e["type"] for e in evs] == ["complete"]
  assert t.timeline("r-c")["terminal"] == "complete"
  # A second end_request must not double-classify.
  t.end_request("r-c")
  assert len(flightrec.query(request_id="r-c", types={"complete"}, limit=10)) == 1


def test_terminal_first_writer_wins():
  t = Tracer()
  t.stage("r-t", "queued")
  t.stage("r-t", "shed", {"reason": "deadline", "class": "standard"}, terminal=True)
  t.end_request("r-t")  # later end_request is a no-op on the classification
  tl = t.timeline("r-t")
  assert tl["terminal"] == "shed" and tl["finished"]
  assert flightrec.query(request_id="r-t", types={"complete"}) == []


# ------------------------------------------------- breaker / health hooks


def test_breaker_transitions_recorded(monkeypatch):
  monkeypatch.setenv("XOT_TPU_CB_FAILS", "2")
  monkeypatch.setenv("XOT_TPU_CB_OPEN_S", "0.01")
  b = CircuitBreaker("peer-x")
  b.record_failure()
  b.record_failure()  # -> open
  time.sleep(0.02)
  assert b.allow()  # -> half_open
  b.record_success()  # -> closed
  types = [e["type"] for e in flightrec.query(peer="peer-x", limit=10)]
  assert types == ["breaker_open", "breaker_half_open", "breaker_close"]


def test_health_damping_death_and_recovery_recorded(monkeypatch):
  monkeypatch.setenv("XOT_TPU_HEALTH_FAILS", "3")
  h = PeerHealth()
  for _ in range(5):
    h.record("peer-y", ok=False)
  h.record("peer-y", ok=True)
  evs = flightrec.query(peer="peer-y", limit=10)
  # Exactly the crossings — never one event per probe.
  assert [e["type"] for e in evs] == ["peer_dead", "peer_recovered"]
  assert evs[0]["attributes"]["consecutive_failures"] == 3


# ----------------------------------------------------------- anomaly watchers


def _no_bundle(monkeypatch):
  """Watcher tests must not write bundles to disk."""
  monkeypatch.setattr(bundles, "auto_capture", lambda *a, **k: False)


def test_breaker_flap_rule_and_cooldown(monkeypatch):
  _no_bundle(monkeypatch)
  w = AnomalyWatchers()
  for _ in range(3):
    flightrec.record("breaker_open", peer="flappy")
  fired = w.check({}, 1.0)
  assert [e["cause"] for e in fired] == ["breaker_flap"]
  assert fired[0]["attributes"]["peer"] == "flappy"
  # Cooldown: an immediate re-check stays quiet even though the condition holds.
  assert w.check({}, 1.0) == []


def test_spec_collapse_and_thrash_rules(monkeypatch):
  _no_bundle(monkeypatch)
  w = AnomalyWatchers()
  delta = {
    "counters": {
      "spec_proposed_tokens_total": 1000.0,
      "spec_accepted_tokens_total": 50.0,  # 5% acceptance — collapse
      "page_grow_events_total": 400.0,
      "page_release_events_total": 400.0,  # 800 events over 2 s = thrash
    }
  }
  fired = w.check(delta, 2.0)
  assert sorted(e["cause"] for e in fired) == ["page_pool_thrash", "spec_acceptance_collapse"]
  rates = {e["cause"]: e["attributes"] for e in fired}
  assert rates["spec_acceptance_collapse"]["rate"] == 0.05
  assert rates["page_pool_thrash"]["events_per_s"] == 400.0


def test_burn_rate_rule_reads_slo_report(monkeypatch):
  _no_bundle(monkeypatch)
  w = AnomalyWatchers()
  report = {"windows_s": [300, 3600], "classes": {"interactive": {"windows": {
    # The slow window still burns (an old outage) but must NOT re-alert —
    # only the fast window's burn fires the rule.
    "300": {"ttft": {"burn_rate": 14.2}, "itl": {"burn_rate": None}, "availability": {"burn_rate": 1.0}},
    "3600": {"ttft": {"burn_rate": 99.0}, "itl": {"burn_rate": None}, "availability": {"burn_rate": 50.0}},
  }}}}
  fired = w.check({}, 1.0, report=report)
  assert [e["cause"] for e in fired] == ["burn_rate"]
  assert fired[0]["attributes"]["burn_rate"] == 14.2  # the FAST window's, not 99
  assert fired[0]["attributes"]["objective"] == "ttft"
  assert fired[0]["attributes"]["window_s"] == "300"


def test_clock_jump_rule(monkeypatch):
  _no_bundle(monkeypatch)
  w = AnomalyWatchers()
  d1 = {"labeled_gauges": {"peer_clock_offset_ms": [[[["peer", "n1"]], 2.0]]}}
  d2 = {"labeled_gauges": {"peer_clock_offset_ms": [[[["peer", "n1"]], 250.0]]}}
  assert w.check(d1, 1.0) == []  # first sighting establishes the baseline
  fired = w.check(d2, 1.0)
  assert [e["cause"] for e in fired] == ["clock_jump"]
  assert fired[0]["attributes"]["jump_ms"] == 248.0


def test_watchers_disabled_with_recorder_off(monkeypatch):
  monkeypatch.setenv("XOT_TPU_FLIGHTREC", "0")
  w = AnomalyWatchers()
  for _ in range(5):
    flightrec.record("breaker_open", peer="flappy")  # no-ops anyway
  assert w.check({}, 1.0) == []


# ----------------------------------------------------------- incident bundles


def test_local_bundle_sections():
  flightrec.record("admitted", request_id="r-bu")
  tracer.stage("r-bu-live", "queued")  # an in-flight timeline to capture
  b = assemble_local_bundle(None, reason="unit")
  assert b["reason"] == "unit"
  for section in ("metrics", "events", "breakers", "peer_health", "clock_offsets", "chaos", "slo", "inflight_timelines", "config"):
    assert section in b, section
  assert any(e["type"] == "admitted" and e["request_id"] == "r-bu" for e in b["events"])
  assert any(tl["request_id"] == "r-bu-live" for tl in b["inflight_timelines"])
  assert "env_sha" in b["config"]
  json.dumps(b)  # the whole artifact must be JSON-safe (it rides the wire)


def test_bundle_rate_limit_and_disk_write(tmp_path, monkeypatch):
  monkeypatch.setenv("XOT_TPU_BUNDLE_DIR", str(tmp_path))
  monkeypatch.setenv("XOT_TPU_BUNDLE_MIN_INTERVAL_S", "3600")
  mgr = BundleManager()

  async def run():
    assert mgr.auto_capture("stall") is True
    # Inside the rate-limit window: refused, no second capture.
    assert mgr.auto_capture("stall") is False
    await asyncio.sleep(0.05)  # let the capture task write

  asyncio.run(run())
  files = list(tmp_path.glob("bundle-*-stall.json"))
  assert len(files) == 1
  saved = json.loads(files[0].read_text())
  assert saved["reason"] == "stall"
  # The capture itself landed in the ring.
  assert any(e["type"] == "bundle_captured" and e["cause"] == "stall" for e in flightrec.recent(50))


def test_auto_capture_disabled_with_recorder_off(tmp_path, monkeypatch):
  monkeypatch.setenv("XOT_TPU_BUNDLE_DIR", str(tmp_path))
  monkeypatch.setenv("XOT_TPU_FLIGHTREC", "0")
  mgr = BundleManager()
  assert mgr.auto_capture("stall") is False
  assert list(tmp_path.glob("*.json")) == []


# ------------------------------------------------------------- API endpoints


async def _make_api():
  from xotorch_support_jetson_tpu.api.chatgpt_api import ChatGPTAPI
  from xotorch_support_jetson_tpu.inference.dummy_engine import DummyInferenceEngine
  from xotorch_support_jetson_tpu.orchestration.node import Node
  from xotorch_support_jetson_tpu.topology.partitioning import RingMemoryWeightedPartitioningStrategy
  from tests_support_stubs import NoDiscovery, StubServer

  node = Node(
    "ev-node", StubServer(), DummyInferenceEngine(), NoDiscovery(), None,
    RingMemoryWeightedPartitioningStrategy(), max_generate_tokens=50,
  )
  await node.start()
  api = ChatGPTAPI(node, "DummyInferenceEngine", response_timeout=30, default_model="dummy")
  client = TestClient(TestServer(api.app))
  await client.start_server()
  return node, client


@pytest.mark.asyncio
async def test_events_endpoint_filters_and_hardening():
  node, client = await _make_api()
  try:
    flightrec.record("admitted", request_id="r-api")
    flightrec.record("shed", request_id="r-api", cause="overload")
    flightrec.record("breaker_open", peer="p9")
    resp = await client.get("/v1/events")
    data = await resp.json()
    assert resp.status == 200 and data["enabled"] is True
    types = [e["type"] for e in data["events"]]
    assert "admitted" in types and "breaker_open" in types
    resp = await client.get("/v1/events?type=shed,breaker_open&n=10")
    data = await resp.json()
    assert {e["type"] for e in data["events"]} <= {"shed", "breaker_open"}
    resp = await client.get("/v1/events?request_id=r-api")
    data = await resp.json()
    assert all(e["request_id"] == "r-api" for e in data["events"])
    resp = await client.get("/v1/events?n=nope")
    assert resp.status == 400
  finally:
    await client.close()
    await node.stop()


@pytest.mark.asyncio
async def test_events_endpoint_disabled(monkeypatch):
  monkeypatch.setenv("XOT_TPU_FLIGHTREC", "0")
  node, client = await _make_api()
  try:
    resp = await client.get("/v1/events")
    data = await resp.json()
    assert resp.status == 200 and data["enabled"] is False
  finally:
    await client.close()
    await node.stop()


@pytest.mark.asyncio
async def test_debug_bundle_endpoint_local_and_saved(tmp_path, monkeypatch):
  monkeypatch.setenv("XOT_TPU_BUNDLE_DIR", str(tmp_path))
  node, client = await _make_api()
  try:
    flightrec.record("stalled", request_id="r-inc")
    resp = await client.post("/v1/debug/bundle", json={"scope": "local", "reason": "drill", "save": True})
    data = await resp.json()
    assert resp.status == 200
    assert data["reason"] == "drill" and data["node_id"] == "ev-node"
    assert any(e["type"] == "stalled" for e in data["events"])
    assert data["saved_to"] and list(tmp_path.glob("bundle-*-drill.json"))
    # Cluster scope with no peers: one part, nothing unreachable, no hang.
    resp = await client.post("/v1/debug/bundle", json={"reason": "drill2"})
    data = await resp.json()
    assert data["scope"] == "cluster" and data["nodes_reporting"] == 1 and data["nodes_unreachable"] == []
  finally:
    await client.close()
    await node.stop()
