"""HF-export golden round trips: load → export → HF ITSELF loads and matches.

The reference has no path from its training state back to a standard HF
checkpoint; models/hf_export.py closes the loop (fine-tune on TPU here,
serve the result anywhere). Every case validates through transformers'
own forward, not this repo's loader.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from xotorch_support_jetson_tpu.inference.shard import Shard
from xotorch_support_jetson_tpu.models.config import load_model_config
from xotorch_support_jetson_tpu.models.decoder import shard_forward
from xotorch_support_jetson_tpu.models.hf_export import export_hf_checkpoint
from xotorch_support_jetson_tpu.models.loader import load_shard_weights

TOKENS = [[1, 5, 9, 42, 7, 3]]


def _hf_logits(model_dir):
  import torch
  from transformers import AutoModelForCausalLM

  model = AutoModelForCausalLM.from_pretrained(model_dir, torch_dtype=torch.float32).eval()
  with torch.no_grad():
    return model(torch.tensor(TOKENS)).logits.numpy()


def _make_tiny(tmp_path, family: str):
  import torch
  from transformers import AutoConfig, AutoModelForCausalLM

  torch.manual_seed(0)
  common = dict(
    vocab_size=128, hidden_size=64, intermediate_size=160, num_hidden_layers=3,
    num_attention_heads=4, num_key_value_heads=2, rms_norm_eps=1e-5,
    rope_theta=10000.0, tie_word_embeddings=family != "mistral", torch_dtype="float32",
  )
  if family == "qwen3":
    common["head_dim"] = 16
  if family == "gemma2":
    common.update(
      head_dim=16, attn_logit_softcapping=50.0, final_logit_softcapping=30.0,
      query_pre_attn_scalar=16, sliding_window=8, hidden_activation="gelu_pytorch_tanh",
    )
  cfg = AutoConfig.for_model({"llama": "llama", "qwen2": "qwen2", "qwen3": "qwen3", "mistral": "mistral", "gemma2": "gemma2"}[family], **common)
  model = AutoModelForCausalLM.from_config(cfg) if family != "gemma2" else AutoModelForCausalLM.from_config(cfg, attn_implementation="eager")
  model = model.to(torch.float32).eval()
  src = tmp_path / "src"
  model.save_pretrained(src, safe_serialization=True)
  import torch as _t

  with _t.no_grad():
    ref = model(_t.tensor(TOKENS)).logits.numpy()
  return src, ref


@pytest.mark.parametrize("family", ["llama", "qwen2", "qwen3", "mistral", "gemma2"])
def test_export_roundtrip_through_hf(tmp_path, family):
  src, ref = _make_tiny(tmp_path, family)
  cfg = load_model_config(src, dtype=jnp.float32)
  shard = Shard("tiny", 0, cfg.n_layers - 1, cfg.n_layers)
  params = load_shard_weights(src, cfg, shard)

  out = export_hf_checkpoint(tmp_path / "out", cfg, params)
  got = _hf_logits(out)
  np.testing.assert_allclose(got, ref, rtol=2e-4, atol=2e-4)


@pytest.mark.parametrize("family", ["phi3", "mixtral", "qwen2-moe"])
def test_export_roundtrip_fused_and_moe(tmp_path, family):
  """phi3 re-fuses qkv/gate_up; MoE families unstack experts + routers
  (+ qwen2-moe's gated shared expert) back to HF names. Verified through
  HF's own forward, reusing the golden harness's tiny builders."""
  from tests.test_hf_golden import _save_tiny_hf

  _save_tiny_hf(tmp_path, "qwen2-moe" if family == "qwen2-moe" else family)
  ref = _hf_logits(tmp_path)
  cfg = load_model_config(tmp_path, dtype=jnp.float32)
  shard = Shard("tiny", 0, cfg.n_layers - 1, cfg.n_layers)
  params = load_shard_weights(tmp_path, cfg, shard)

  out = export_hf_checkpoint(tmp_path / "out", cfg, params)
  got = _hf_logits(out)
  np.testing.assert_allclose(got, ref, rtol=2e-4, atol=2e-4)


def test_export_carries_source_token_ids(tmp_path):
  """Regression (round-4 phi3 break): exported configs must carry the source's
  bos/pad/eos token ids. Dropping them lets transformers re-apply architecture
  defaults on import — Phi3Config's pad_token_id=32000 crashes nn.Embedding
  for any vocab smaller than that."""
  import json

  from tests.test_hf_golden import _save_tiny_hf

  _save_tiny_hf(tmp_path, "phi3")
  src_cfg = json.loads((tmp_path / "config.json").read_text())
  cfg = load_model_config(tmp_path, dtype=jnp.float32)
  shard = Shard("tiny", 0, cfg.n_layers - 1, cfg.n_layers)
  params = load_shard_weights(tmp_path, cfg, shard)

  out = export_hf_checkpoint(tmp_path / "out", cfg, params)
  got_cfg = json.loads((out / "config.json").read_text())
  for key in ("bos_token_id", "pad_token_id", "eos_token_id"):
    assert got_cfg.get(key) == src_cfg.get(key), f"{key}: exported {got_cfg.get(key)!r} != source {src_cfg.get(key)!r}"


def test_export_merges_lora(tmp_path):
  """LoRA adapters in the tree merge into the exported base weights: HF's
  forward of the export must equal THIS repo's forward with adapters live."""
  src, _ = _make_tiny(tmp_path, "llama")
  cfg = load_model_config(src, dtype=jnp.float32)
  shard = Shard("tiny", 0, cfg.n_layers - 1, cfg.n_layers)
  params = load_shard_weights(src, cfg, shard)

  L, D, Qd = cfg.n_layers, cfg.dim, cfg.q_dim
  key = jax.random.PRNGKey(3)
  rank = 2
  stack = dict(params["layers"])
  stack["wq_lora_a"] = jax.random.normal(key, (L, D, rank)) * 0.05
  stack["wq_lora_b"] = jax.random.normal(jax.random.fold_in(key, 1), (L, rank, Qd)) * 0.05
  stack["wv_lora_a"] = jax.random.normal(jax.random.fold_in(key, 2), (L, D, rank)) * 0.05
  stack["wv_lora_b"] = jax.random.normal(jax.random.fold_in(key, 3), (L, rank, cfg.kv_dim)) * 0.05
  params = {**params, "layers": stack}

  tokens = jnp.asarray(TOKENS, dtype=jnp.int32)
  positions = jnp.broadcast_to(jnp.arange(tokens.shape[1], dtype=jnp.int32), tokens.shape)
  ours, _ = shard_forward(params, cfg, shard, tokens, positions, None)

  out = export_hf_checkpoint(tmp_path / "out_lora", cfg, params)
  got = _hf_logits(out)
  np.testing.assert_allclose(got, np.asarray(ours), rtol=2e-4, atol=2e-4)


def test_export_refuses_unsupported():
  from xotorch_support_jetson_tpu.models.config import tiny_test_config

  mla = tiny_test_config(kv_lora_rank=16, qk_nope_head_dim=8, qk_rope_head_dim=4, v_head_dim=8, family="deepseek-v2")
  with pytest.raises(NotImplementedError):
    export_hf_checkpoint("/tmp/never", mla, {})
