"""Batched multi-LoRA serving (ISSUE 15): the per-row adapter-indexed hook
in the fused programs (models/decoder.py ``_alora_delta``), the adapter
registry with its host tier and pins (inference/adapters.py), and the
scheduler/engine plumbing.

The correctness contract: each row of a MIXED-adapter batch is
token-identical to its own ``merge_lora`` solo reference (the adapter
folded into the base weights), adapter-less rows == the base model, on the
paged int8-KV serving default AND the dense layout, lookahead on and off;
preempt-resume keeps its adapter across the carry; ``XOT_TPU_LORA=0`` is
byte-identical base serving with the hook poison-pinned never-called."""

import asyncio

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tests.test_lookahead import _serve
from xotorch_support_jetson_tpu.inference.adapters import (
  AdapterRegistry,
  AdapterSlotsPinnedError,
  UnknownAdapterError,
  adapter_nbytes,
  extract_adapter,
  load_adapter,
  lora_tenant_map,
  save_adapter,
)
from xotorch_support_jetson_tpu.inference.batch_scheduler import BatchedServer
from xotorch_support_jetson_tpu.inference.jax_engine import JaxShardedInferenceEngine
from xotorch_support_jetson_tpu.inference.qos import QosConfig, QosPolicy, qos_metadata, qos_wire
from xotorch_support_jetson_tpu.models.config import tiny_test_config
from xotorch_support_jetson_tpu.models.decoder import full_model_params, fused_decode, init_kv_cache, shard_forward
from xotorch_support_jetson_tpu.train.lora import add_lora, merge_lora
from xotorch_support_jetson_tpu.utils.metrics import metrics as gm

CFG = tiny_test_config(n_layers=2, max_seq_len=256, tied_embedding=True)
KEY = jax.random.PRNGKey(0)
RANK = 4
PARAMS, SHARD = full_model_params(KEY, CFG, "m")


def _synth_adapter_params(seed: int, rank: int = RANK) -> dict:
  """A params tree carrying one synthetic adapter in train/lora.py leaf
  format — B is made nonzero so the variant actually differs from base."""
  p = add_lora(PARAMS, rank, jax.random.PRNGKey(seed))
  layers = dict(p["layers"])
  for t in ("wq", "wv"):
    b = layers[f"{t}_lora_b"]
    layers[f"{t}_lora_b"] = (
      jax.random.normal(jax.random.fold_in(jax.random.PRNGKey(seed), 99), b.shape, jnp.float32) * 0.05
    ).astype(b.dtype)
  return {**p, "layers": layers}


_AD1 = _synth_adapter_params(1)
_AD2 = _synth_adapter_params(2)
ADAPTER_1 = extract_adapter(_AD1)
ADAPTER_2 = extract_adapter(_AD2)
MERGED_1 = merge_lora(_AD1, RANK)
MERGED_2 = merge_lora(_AD2, RANK)


def _solo_ref(params, prompt, n_steps):
  """Greedy solo decode against ``params`` (base or MERGED adapter) — the
  no-batching, no-adapter-hook ground truth."""
  S = len(prompt)
  tokens = jnp.asarray([prompt], dtype=jnp.int32)
  positions = jnp.broadcast_to(jnp.arange(S, dtype=jnp.int32), (1, S))
  cache = init_kv_cache(CFG, SHARD.n_shard_layers, 1, max(64, S + n_steps + 2))
  logits, cache = shard_forward(params, CFG, SHARD, tokens, positions, cache)
  first = jnp.argmax(logits[:, -1, :], axis=-1).astype(jnp.int32)[:, None]
  toks, _ = fused_decode(params, CFG, SHARD, first, cache, jnp.full((1,), S, jnp.int32), n_steps, temp=0.0)
  return [int(first[0, 0])] + [int(t) for t in np.asarray(toks)[0]]


def _engine_with_adapters(capacity: int = 4):
  engine = JaxShardedInferenceEngine(use_local_mesh=False)
  engine.load_test_model(SHARD, CFG, PARAMS)
  reg = engine.enable_multi_lora(capacity=capacity, rank=RANK)
  assert reg is not None
  reg.register("a1", ADAPTER_1)
  reg.register("a2", ADAPTER_2)
  return engine, reg


PROMPTS = [[3, 25, 9, 7], [7, 1, 88, 42, 5], [100, 4, 17], [9, 9, 2, 1, 5, 6]]
NAMES = ["a1", "a2", None, "a1"]  # mixed batch: two adapters + a base row


def _serve_mixed(server, n_gen):
  streams: dict[str, list] = {}

  async def run():
    def emit(rid, toks, fin):
      streams.setdefault(rid, []).extend(toks)

    return await asyncio.gather(*(
      server.submit(
        f"r{i}", np.asarray(p, np.int32), max_tokens=n_gen, temp=0.0, top_k=35,
        eos_ids=(), emit=emit, adapter=nm,
      )
      for i, (p, nm) in enumerate(zip(PROMPTS, NAMES))
    ))

  outs = asyncio.run(run())
  return outs, [streams[f"r{i}"] for i in range(len(PROMPTS))]


def _mixed_refs(n_gen):
  by_name = {None: PARAMS, "a1": MERGED_1, "a2": MERGED_2}
  return [_solo_ref(by_name[nm], p, n_gen - 1) for p, nm in zip(PROMPTS, NAMES)]


# ------------------------------------------------- token-identity contract


@pytest.mark.parametrize("layout", ["paged_int8", "dense"])
def test_mixed_batch_rows_match_merged_solo(monkeypatch, layout):
  """Each row of a mixed-adapter batch == its own merge_lora solo
  reference; the adapter-less row == the base model — paged int8-KV (the
  serving default) and dense, lookahead on AND off."""
  if layout == "paged_int8":
    monkeypatch.setenv("XOT_TPU_PAGED", "1")
    monkeypatch.setenv("XOT_TPU_KV_QUANT", "int8")
  else:
    monkeypatch.setenv("XOT_TPU_PAGED", "0")
  n_gen = 6
  refs = _mixed_refs(n_gen)
  engine, reg = _engine_with_adapters()
  for lookahead in (True, False):
    server = BatchedServer(engine, n_slots=4, chunk=2, lookahead=lookahead)
    outs, streams = _serve_mixed(server, n_gen)
    assert server._lora_active()
    server.shutdown()
    for i, (o, s, r) in enumerate(zip(outs, streams, refs)):
      assert s == o
      assert o == r, f"(layout={layout}, lookahead={lookahead}) row {i}: {o} != {r}"
  assert not reg.pinned_holders()  # every finish path unpinned


def test_adapter_requests_count_and_resident_gauge():
  engine, reg = _engine_with_adapters()
  before = gm.counter_value("lora_requests_total", labels={"adapter": "a1"})
  server = BatchedServer(engine, n_slots=4, chunk=2)
  _serve_mixed(server, 4)
  server.shutdown()
  assert gm.counter_value("lora_requests_total", labels={"adapter": "a1"}) == before + 2
  assert gm.gauge_value("lora_adapters_resident") == 2


def test_lora_off_is_base_and_hook_never_called(monkeypatch):
  """XOT_TPU_LORA=0: enable_multi_lora returns None, serving is the base
  model byte-for-byte, and the decoder hook is POISONED never-called."""
  from xotorch_support_jetson_tpu.models import decoder as dec

  monkeypatch.setenv("XOT_TPU_LORA", "0")

  def boom(*a, **k):  # noqa: ANN002, ANN003
    raise AssertionError("_alora_delta must never run with XOT_TPU_LORA=0")

  monkeypatch.setattr(dec, "_alora_delta", boom)
  engine = JaxShardedInferenceEngine(use_local_mesh=False)
  engine.load_test_model(SHARD, CFG, PARAMS)
  assert engine.enable_multi_lora(capacity=4, rank=RANK) is None
  n_gen = 4
  base_refs = [_solo_ref(PARAMS, p, n_gen - 1) for p in PROMPTS]
  server = BatchedServer(engine, n_slots=4, chunk=2)
  outs, _ = _serve(server, PROMPTS, n_gen)
  server.shutdown()
  assert outs == base_refs


def test_unknown_adapter_fails_the_request_only():
  """An unknown name fails ITS request with the client-error type; the
  rest of the batch serves normally and the pool stays clean."""
  engine, _ = _engine_with_adapters()
  server = BatchedServer(engine, n_slots=2, chunk=2)
  ref = _solo_ref(PARAMS, PROMPTS[0], 3)

  async def run():
    def emit(rid, toks, fin):
      pass

    bad = server.submit("bad", np.asarray(PROMPTS[1], np.int32), max_tokens=4, temp=0.0, top_k=35, eos_ids=(), emit=emit, adapter="nope")
    good = server.submit("good", np.asarray(PROMPTS[0], np.int32), max_tokens=4, temp=0.0, top_k=35, eos_ids=(), emit=emit)
    results = await asyncio.gather(bad, good, return_exceptions=True)
    return results

  bad_res, good_res = asyncio.run(run())
  assert isinstance(bad_res, UnknownAdapterError)
  assert good_res == ref
  assert all(s is None for s in server.slots)
  server.shutdown()


def test_preempt_resume_keeps_adapter():
  """A preempted adapter row resumes ON ITS ADAPTER across the carry: the
  resumed stream is token-identical to the adapter's merged solo
  reference (the name rides _Request.adapter; the resumed admission
  re-resolves and re-pins a slot)."""
  engine, reg = _engine_with_adapters()
  server = BatchedServer(engine, n_slots=1, chunk=2, qos=QosPolicy(QosConfig(aging_s=10_000.0)))
  p_batch, p_int = [3, 25, 9], [7, 1, 88, 42, 5]
  n_batch, n_int = 24, 4
  solo_batch = _solo_ref(MERGED_1, p_batch, n_batch - 1)
  solo_int = _solo_ref(PARAMS, p_int, n_int - 1)
  before = gm.counter_value("qos_preemptions_total")
  streams: dict[str, list] = {}

  async def run():
    started = asyncio.Event()

    def emit(rid, toks, fin):
      streams.setdefault(rid, []).extend(toks)
      if rid == "bg" and len(streams["bg"]) >= 4:
        started.set()

    bg = asyncio.create_task(server.submit(
      "bg", np.asarray(p_batch, np.int32), max_tokens=n_batch, temp=0.0, top_k=35,
      eos_ids=(), emit=emit, priority="batch", tenant="bulk", adapter="a1",
    ))
    await asyncio.wait_for(started.wait(), timeout=30)
    out_int = await asyncio.wait_for(server.submit(
      "vip", np.asarray(p_int, np.int32), max_tokens=n_int, temp=0.0, top_k=35,
      eos_ids=(), emit=emit, priority="interactive", tenant="vip",
    ), timeout=60)
    out_bg = await asyncio.wait_for(bg, timeout=60)
    return out_int, out_bg

  out_int, out_bg = asyncio.run(run())
  assert gm.counter_value("qos_preemptions_total") > before  # it really preempted
  assert out_int == solo_int
  assert out_bg == solo_batch  # carry + resumed tokens == the merged stream
  assert streams["bg"] == solo_batch
  assert not reg.pinned_holders()
  server.shutdown()


# --------------------------------------------------------- solo parity


def test_solo_session_applies_adapter():
  """Solo/streaming parity: a non-batched session selecting a named
  adapter decodes the merged reference (indexed application through
  _prefill + fused_decode), and the base session stays base."""
  engine, reg = _engine_with_adapters()
  n = 5
  for name, mp in (("a1", MERGED_1), (None, PARAMS)):
    rid = f"solo-{name}"
    if name:
      engine.set_request_adapter(rid, name)
    prompt = np.asarray([PROMPTS[0]], np.int32)
    out, state = asyncio.run(engine.infer_tensor(rid, SHARD, prompt))
    first = int(np.argmax(out[0]))
    toks = asyncio.run(engine.generate_chunk(rid, SHARD, first, n, temp=0.0))
    got = [first] + [int(t) for t in toks]
    assert got == _solo_ref(mp, PROMPTS[0], n), f"solo adapter={name}"
  with pytest.raises(UnknownAdapterError):
    engine.set_request_adapter("solo-x", "nope")
  # Solo pins sweep once their sessions are gone.
  asyncio.run(engine.clear_session())
  engine.set_request_adapter("solo-y", "a2")
  asyncio.run(engine.infer_tensor("solo-y", SHARD, np.asarray([PROMPTS[1]], np.int32)))
  assert not [h for h in reg.pinned_holders() if isinstance(h, tuple) and h[1] == f"solo-a1"]


# ------------------------------------------------------- registry units


def _null_install():
  calls = []

  def install(slot, arrays):
    calls.append((slot, None if arrays is None else sorted(arrays)))

  return install, calls


def _geometry():
  L, D = SHARD.n_shard_layers, CFG.dim
  return {"layers": {"wq": (L, D, CFG.q_dim), "wv": (L, D, CFG.kv_dim)}}


def test_registry_lru_swap_and_pins():
  install, calls = _null_install()
  reg = AdapterRegistry(geometry=_geometry(), rank=RANK, capacity=4, install=install, host_budget_bytes=1 << 30)
  for i in range(5):
    reg.register(f"x{i}", extract_adapter(_synth_adapter_params(10 + i)))
  s0, s1, s2 = reg.acquire("x0"), reg.acquire("x1"), reg.acquire("x2")
  assert len({s0, s1, s2}) == 3 and 0 not in (s0, s1, s2)  # slot 0 reserved
  before = gm.counter_value("lora_swaps_total", labels={"direction": "out"})
  s3 = reg.acquire("x3")  # capacity 4 → 3 usable: x0 (LRU) evicts
  assert s3 == s0 and reg.slot_of("x0") is None
  assert gm.counter_value("lora_swaps_total", labels={"direction": "out"}) == before + 1
  # A pinned slot is never reassigned; with every slot pinned acquire raises.
  reg.acquire("x1", holder="h1")
  reg.acquire("x2", holder="h2")
  reg.acquire("x3", holder="h3")
  with pytest.raises(AdapterSlotsPinnedError):
    reg.acquire("x4")
  reg.unpin("h2")
  assert reg.acquire("x4") == s2  # the unpinned slot was the only candidate
  with pytest.raises(UnknownAdapterError):
    reg.acquire("never-registered")
  # Refreshing a DEVICE-RESIDENT adapter reinstalls its slot in place (the
  # operator wants the new weights, never a stale slot served forever).
  slot_before = reg.slot_of("x1")
  n_installs = len(calls)
  reg.register("x1", extract_adapter(_synth_adapter_params(77)))
  assert reg.slot_of("x1") == slot_before
  assert len(calls) == n_installs + 1 and calls[-1][0] == slot_before


def test_registry_host_budget_evicts_and_reloads(tmp_path):
  """The byte-budgeted host LRU: cold entries with a checkpoint path drop
  their arrays under pressure and reload on demand (direction-labeled
  swaps); an in-memory-only entry is never made unrecoverable."""
  install, _ = _null_install()
  one = adapter_nbytes(ADAPTER_1)
  path = save_adapter(tmp_path / "d1", ADAPTER_1)
  reg = AdapterRegistry(geometry=_geometry(), rank=RANK, capacity=4, install=install, host_budget_bytes=int(one * 1.5))
  reg.register("mem-only", ADAPTER_2)  # no path: must survive the budget squeeze
  reg.register("disk", path=str(path))
  reg.register("mem2", ADAPTER_1)  # over budget now: "disk" is the evictable LRU entry
  snap = reg.snapshot()["adapters"]
  assert snap["mem-only"]["host_resident"]
  assert not snap["disk"]["host_resident"]  # arrays dropped, path kept
  before = gm.counter_value("lora_swaps_total", labels={"direction": "load"})
  assert reg.acquire("disk") > 0  # reloads from the npz
  assert gm.counter_value("lora_swaps_total", labels={"direction": "load"}) == before + 1


def test_registry_rank_pad_and_refuse():
  install, _ = _null_install()
  reg = AdapterRegistry(geometry=_geometry(), rank=RANK, capacity=2, install=install)
  small = extract_adapter(_synth_adapter_params(30, rank=2))  # pads 2 → 4
  reg.register("small", small)
  assert reg.acquire("small") == 1
  with pytest.raises(ValueError, match="rank"):
    reg.register("big", extract_adapter(_synth_adapter_params(31, rank=8)))
  with pytest.raises(ValueError, match="geometry"):
    bad = {"layers": {"wq": (np.zeros((1, 2, RANK), np.float32), np.zeros((1, RANK, 3), np.float32))}}
    reg.register("bad", bad)


def test_adapter_checkpoint_roundtrip(tmp_path):
  """save_adapter/load_adapter round-trips, and load_adapter also reads a
  full train/checkpoint.py npz (flat keystr keys) — the train/lora.py
  checkpoint format the registry documents."""
  p = save_adapter(tmp_path / "rt", ADAPTER_1)
  back = load_adapter(p)
  for t in ("wq", "wv"):
    np.testing.assert_array_equal(back["layers"][t][0], ADAPTER_1["layers"][t][0])
  # train/checkpoint.py npz-fallback format: keystr flat keys.
  flat = {}
  for stack, per in ADAPTER_1.items():
    for t, (a, b) in per.items():
      flat[f"['{stack}']['{t}_lora_a']"] = a
      flat[f"['{stack}']['{t}_lora_b']"] = b
  flat["['layers']['wq']"] = np.zeros((2, 2), np.float32)  # non-adapter leaves ignored
  np.savez(str(tmp_path / "full.npz"), **flat)
  back2 = load_adapter(tmp_path / "full.npz")
  np.testing.assert_array_equal(back2["layers"]["wv"][1], ADAPTER_1["layers"]["wv"][1])
  with pytest.raises(FileNotFoundError):
    load_adapter(tmp_path / "missing.npz")


def test_lora_block_math_and_pool_deduction():
  """The adapter-stack HBM enters the page budget (the draft-KV pattern):
  a multi-LoRA server's pool is strictly smaller than the base server's,
  by the block-math page equivalent."""
  from xotorch_support_jetson_tpu.inference.paging import lora_device_bytes, lora_pages_equivalent

  assert lora_device_bytes(2, 8, 16, 4, 8, itemsize=4) == 2 * 8 * 4 * (8 + 16) * 4
  assert lora_pages_equivalent(100, 64) == 2
  assert lora_pages_equivalent(0, 64) == 0

  base_eng = JaxShardedInferenceEngine(use_local_mesh=False)
  base_eng.load_test_model(SHARD, CFG, PARAMS)
  base_srv = BatchedServer(base_eng, n_slots=2, chunk=2)
  base_srv._ensure_cache()
  base_pages = base_srv.allocator.n_pages
  base_srv.shutdown()

  lora_eng, reg = _engine_with_adapters()
  srv = BatchedServer(lora_eng, n_slots=2, chunk=2)
  srv._ensure_cache()
  from xotorch_support_jetson_tpu.inference.paging import kv_cache_bytes

  page_bytes = max(kv_cache_bytes(CFG, SHARD.n_shard_layers, srv.page_size, srv.kv_quant), 1)
  expect_deduct = lora_pages_equivalent(reg.device_bytes(), page_bytes)
  assert expect_deduct > 0
  assert srv.allocator.n_pages <= base_pages - min(expect_deduct, base_pages - srv.pages_per_row - 2)
  srv.shutdown()


# ------------------------------------------------- wire / router / advert


def test_adapter_rides_the_qos_wire():
  qos_wire.register("wreq", priority="standard", adapter="a1", node_id="n0")
  try:
    meta = dict(qos_metadata("wreq"))
    assert meta["x-adapter"] == "a1"
  finally:
    qos_wire.pop("wreq")


def test_stats_snapshot_advertises_resident_adapters():
  engine, reg = _engine_with_adapters()
  reg.acquire("a2")
  server = BatchedServer(engine, n_slots=2, chunk=2)
  server._ensure_cache()
  st = server.stats_snapshot()
  assert "a2" in st["lora_adapters"]
  # The full REGISTERED list rides along for the front door's model-field
  # alias check — a registered-but-cold adapter must still resolve.
  assert set(st["lora_adapters_known"]) == {"a1", "a2"}
  server.shutdown()


def test_router_policy_adapter_affinity_rung():
  """The ladder's ADAPTER rung: a named adapter restricts placement to
  replicas advertising it device-resident (source="adapter"); with no
  advertiser the restriction drops (any replica can load it)."""
  from xotorch_support_jetson_tpu.inference.router_policy import RouterPolicy

  t = [0.0]
  policy = RouterPolicy({"r0": "http://a", "r1": "http://b"}, clock=lambda: t[0])
  policy.update_stats("r0", {"slots_total": 4, "slots_busy": 0, "lora_adapters": []})
  policy.update_stats("r1", {"slots_total": 4, "slots_busy": 3, "lora_adapters": ["a1"]})
  # r1 is more loaded, but it holds the adapter: the rung restricts to it.
  target, source, _ = policy.choose([], adapter="a1")
  assert (target, source) == ("r1", "adapter")
  # Nobody advertises a2: restriction drops, least-loaded wins as "load".
  target, source, _ = policy.choose([], adapter="a2")
  assert target == "r0" and source == "load"
  # No adapter: unchanged ladder.
  target, source, _ = policy.choose([])
  assert source == "load"


@pytest.mark.asyncio
async def test_api_unknown_adapter_400_and_introspection():
  """HTTP surface: an `x-adapter` naming an unknown adapter 400s with the
  typed code BEFORE any device work, and `GET /v1/adapters` reports
  multi-LoRA off on an adapter-less node."""
  from aiohttp.test_utils import TestClient, TestServer

  from tests_support_stubs import NoDiscovery, StubServer
  from xotorch_support_jetson_tpu.api.chatgpt_api import ChatGPTAPI
  from xotorch_support_jetson_tpu.inference.dummy_engine import DummyInferenceEngine
  from xotorch_support_jetson_tpu.orchestration.node import Node
  from xotorch_support_jetson_tpu.topology.partitioning import RingMemoryWeightedPartitioningStrategy

  node = Node(
    "lora-api-node", StubServer(), DummyInferenceEngine(), NoDiscovery(), None,
    RingMemoryWeightedPartitioningStrategy(), max_generate_tokens=8,
  )
  await node.start()
  api = ChatGPTAPI(node, "DummyInferenceEngine", response_timeout=30, default_model="dummy")
  client = TestClient(TestServer(api.app))
  await client.start_server()
  try:
    resp = await client.get("/v1/adapters")
    assert resp.status == 200 and (await resp.json())["enabled"] is False
    resp = await client.post(
      "/v1/chat/completions",
      json={"model": "dummy", "messages": [{"role": "user", "content": "hi"}]},
      headers={"x-adapter": "nope"},
    )
    assert resp.status == 400
    body = await resp.json()
    assert body["error"]["code"] == "unknown_adapter"
    # No adapter selection: the ordinary request path is untouched.
    resp = await client.post(
      "/v1/chat/completions",
      json={"model": "dummy", "messages": [{"role": "user", "content": "hi"}]},
    )
    assert resp.status == 200
  finally:
    await client.close()
    await node.stop()


def test_tenant_map_and_parse_adapter_field(monkeypatch):
  from xotorch_support_jetson_tpu.api.chatgpt_api import parse_adapter_field

  monkeypatch.setenv("XOT_TPU_LORA_TENANTS", '{"acme": "a1"}')
  assert lora_tenant_map() == {"acme": "a1"}
  known = lambda n: n in ("a1", "a2")  # noqa: E731
  assert parse_adapter_field({}, {"x-adapter": "a2"}, None, known) == "a2"
  assert parse_adapter_field({"model": "a1"}, {}, None, known) == "a1"
  assert parse_adapter_field({"model": "llama-3.2-1b"}, {}, None, known) is None
  assert parse_adapter_field({}, {}, "acme", known) == "a1"
  assert parse_adapter_field({}, {}, "other", known) is None
  monkeypatch.setenv("XOT_TPU_LORA_TENANTS", "not json")
  assert lora_tenant_map() == {}
