"""Training path tests: the capability the reference promised but never
implemented (SURVEY.md §2.2, §3.4) — loss decreases, LoRA trains only
adapters, checkpoints round-trip, dataset batching is correct."""

import asyncio
from pathlib import Path

import jax
import numpy as np
import pytest

from xotorch_support_jetson_tpu.inference.jax_engine import JaxShardedInferenceEngine
from xotorch_support_jetson_tpu.inference.shard import Shard
from xotorch_support_jetson_tpu.models.config import tiny_test_config
from xotorch_support_jetson_tpu.models.decoder import full_model_params, shard_forward
from xotorch_support_jetson_tpu.train.dataset import Dataset, iterate_batches, load_dataset
from xotorch_support_jetson_tpu.train.lora import add_lora, merge_lora

DATA_DIR = Path(__file__).parent.parent / "xotorch_support_jetson_tpu" / "train" / "data" / "lora"


class WordTokenizer:
  eos_token_id = 0

  def encode(self, text):
    return [(hash(w) % 97) + 1 for w in text.split()]

  def decode(self, toks):
    return " ".join(map(str, toks))


def _engine():
  cfg = tiny_test_config(n_layers=2, vocab_size=128)
  params, shard = full_model_params(jax.random.PRNGKey(0), cfg, "m")
  engine = JaxShardedInferenceEngine()
  engine.load_test_model(shard, cfg, params, WordTokenizer())
  return engine, shard, cfg


def _batch(cfg, B=2, S=8, seed=0):
  rng = np.random.default_rng(seed)
  inputs = rng.integers(1, cfg.vocab_size, (B, S)).astype(np.int32)
  targets = rng.integers(1, cfg.vocab_size, (B, S)).astype(np.int32)
  lengths = np.full((B,), S, np.int32)
  return inputs, targets, lengths


@pytest.mark.asyncio
async def test_engine_train_loss_decreases():
  engine, shard, cfg = _engine()
  inputs, targets, lengths = _batch(cfg)
  losses = [await engine.train("r", shard, inputs, targets, lengths, lr=1e-2) for _ in range(8)]
  assert all(np.isfinite(losses))
  assert losses[-1] < losses[0], losses


@pytest.mark.asyncio
async def test_structural_is_sliding_flag_survives_adamw():
  """The per-layer sliding-window flag rides in params (the scan body reads
  it) but is NOT a weight: adamw's decoupled weight decay must not drift it
  (ADVICE r2 — decay perturbs every leaf each step even at zero gradient)."""
  cfg = tiny_test_config(n_layers=2, vocab_size=128, sliding_window=8)
  params, shard = full_model_params(jax.random.PRNGKey(0), cfg, "m")
  engine = JaxShardedInferenceEngine()
  engine.load_test_model(shard, cfg, params, WordTokenizer())
  flags_before = np.asarray(engine.params["layers"]["is_sliding"]).copy()
  assert flags_before.tolist() == [1.0, 0.0]  # even layers slide (gemma2 rule)
  wq_before = np.asarray(engine.params["layers"]["wq"]).copy()
  inputs, targets, lengths = _batch(cfg)
  for _ in range(4):
    await engine.train("r", shard, inputs, targets, lengths, lr=1e-2, opt="adamw")
  np.testing.assert_array_equal(np.asarray(engine.params["layers"]["is_sliding"]), flags_before)
  assert not np.allclose(np.asarray(engine.params["layers"]["wq"]), wq_before)  # real weights did move


@pytest.mark.asyncio
async def test_engine_evaluate():
  engine, shard, cfg = _engine()
  inputs, targets, lengths = _batch(cfg)
  loss = await engine.evaluate("r", shard, inputs, targets, lengths)
  assert np.isfinite(loss) and loss > 0


@pytest.mark.asyncio
async def test_lora_trains_only_adapters():
  engine, shard, cfg = _engine()
  engine.params = add_lora(engine.params, rank=4, key=jax.random.PRNGKey(1))
  base_before = np.asarray(engine.params["layers"]["wq"]).copy()
  lora_b_before = np.asarray(engine.params["layers"]["wq_lora_b"]).copy()
  inputs, targets, lengths = _batch(cfg)
  for _ in range(3):
    await engine.train("r", shard, inputs, targets, lengths, lr=1e-2)
  np.testing.assert_array_equal(np.asarray(engine.params["layers"]["wq"]), base_before)
  assert not np.allclose(np.asarray(engine.params["layers"]["wq_lora_b"]), lora_b_before)


def test_lora_merge_changes_forward_consistently():
  cfg = tiny_test_config(n_layers=2, vocab_size=64)
  params, shard = full_model_params(jax.random.PRNGKey(0), cfg, "m")
  with_lora = add_lora(params, rank=4, key=jax.random.PRNGKey(1))
  # Nudge B so the adapters are non-zero.
  import jax.numpy as jnp

  with_lora["layers"]["wq_lora_b"] = jnp.ones_like(with_lora["layers"]["wq_lora_b"]) * 0.01
  tokens = jnp.asarray([[1, 2, 3]], jnp.int32)
  pos = jnp.broadcast_to(jnp.arange(3, dtype=jnp.int32), (1, 3))
  with jax.default_matmul_precision("highest"):
    logits_lora, _ = shard_forward(with_lora, cfg, shard, tokens, pos, None)
    merged = merge_lora(with_lora, rank=4)
    assert "wq_lora_a" not in merged["layers"]
    logits_merged, _ = shard_forward(merged, cfg, shard, tokens, pos, None)
    logits_base, _ = shard_forward(params, cfg, shard, tokens, pos, None)
  np.testing.assert_allclose(np.asarray(logits_lora), np.asarray(logits_merged), rtol=1e-4, atol=1e-4)
  assert not np.allclose(np.asarray(logits_lora), np.asarray(logits_base))


@pytest.mark.asyncio
async def test_checkpoint_roundtrip(tmp_path):
  engine, shard, cfg = _engine()
  original = jax.tree.map(np.asarray, engine.params)
  await engine.save_checkpoint(shard, tmp_path / "ckpt")
  # Perturb, then restore.
  engine.params = jax.tree.map(lambda x: x + 1.0 if x.dtype.kind == "f" else x, engine.params)
  await engine.load_checkpoint(shard, tmp_path / "ckpt")
  restored = jax.tree.map(np.asarray, engine.params)
  jax.tree.map(np.testing.assert_array_equal, original, restored)


def test_dataset_loading_and_batching():
  train, valid, test = load_dataset(DATA_DIR)
  assert len(train) >= 4 and len(valid) >= 1 and len(test) >= 1
  tok = WordTokenizer()
  batches = list(iterate_batches(train, tok, batch_size=2, seq_len=16))
  assert batches
  inputs, targets, lengths = batches[0]
  assert inputs.shape == (2, 16) and targets.shape == (2, 16) and lengths.shape == (2,)
  # Next-token alignment: targets are inputs shifted by one.
  row_tokens = tok.encode(train[0])
  n = min(len(row_tokens) - 1, 16)
  np.testing.assert_array_equal(inputs[0, :n], row_tokens[:n])
  np.testing.assert_array_equal(targets[0, :n], row_tokens[1 : n + 1])
  assert lengths[0] == n


def test_checkpoint_orbax_failure_raises_not_degrades(tmp_path, monkeypatch):
  """VERDICT r4 #9: a REAL orbax save failure (disk full, bad sharding) must
  surface, not silently degrade to npz — only orbax being absent/renamed
  (ImportError/AttributeError at import) selects the fallback."""
  import orbax.checkpoint as ocp
  import pytest

  from xotorch_support_jetson_tpu.train.checkpoint import save_params

  params = {"w": jax.numpy.ones((4, 4), jax.numpy.float32)}

  def boom(self, *a, **k):
    raise OSError("disk full")

  monkeypatch.setattr(ocp.StandardCheckpointer, "save", boom)
  with pytest.raises(OSError, match="disk full"):
    save_params(params, tmp_path / "ckpt")
  assert not (tmp_path / "ckpt.npz").exists(), "orbax failure must not masquerade as an npz format choice"


def test_checkpoint_npz_fallback_when_orbax_absent(tmp_path, monkeypatch):
  """With orbax unimportable the flat-npz fallback still round-trips."""
  import builtins

  from xotorch_support_jetson_tpu.train.checkpoint import load_params, save_params

  real_import = builtins.__import__

  def no_orbax(name, *a, **k):
    if name.startswith("orbax"):
      raise ImportError("orbax not installed")
    return real_import(name, *a, **k)

  monkeypatch.setattr(builtins, "__import__", no_orbax)
  params = {"w": jax.numpy.arange(16, dtype=jax.numpy.float32).reshape(4, 4)}
  save_params(params, tmp_path / "ckpt")
  assert (tmp_path / "ckpt.npz").exists()
  restored = load_params(tmp_path / "ckpt", params)
  np.testing.assert_array_equal(np.asarray(restored["w"]), np.asarray(params["w"]))
