"""Multi-host bring-up smoke (scripts/multihost_smoke.py).

Exercises the exact ``--jax-coordinator`` path (`main.maybe_init_jax_distributed`)
with two real OS processes joining one coordinator on CPU: a global dp mesh
spans both processes and one train step's gradient all-reduce crosses the
process boundary. This is the CI-runnable stand-in for a TPU pod bring-up
(VERDICT r1 weak #5)."""

import os
import subprocess
import sys

import pytest


def test_two_process_jax_distributed_train_step():
  from xotorch_support_jetson_tpu.utils.helpers import multihost_cpu_collectives_supported

  if not multihost_cpu_collectives_supported():
    # jax 0.4.x cannot route CPU collectives through gloo: the two-process
    # psum dies with "Multiprocess computations aren't implemented on the
    # CPU backend". Environmental, not a regression — skip with the probe.
    pytest.skip("this jax build has no CPU cross-process collectives (jax_cpu_collectives_implementation absent)")
  root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
  env = {k: v for k, v in os.environ.items() if k not in ("XLA_FLAGS",)}
  out = subprocess.run(
    [sys.executable, os.path.join(root, "scripts", "multihost_smoke.py")],
    capture_output=True, text=True, timeout=420, env=env, cwd=root,
  )
  assert out.returncode == 0, out.stdout[-2000:] + out.stderr[-2000:]
  assert "identical loss" in out.stdout
