"""Engine in-slice TP: sharded-over-mesh engine must match single-device."""

import jax
import numpy as np
import pytest

from xotorch_support_jetson_tpu.inference.jax_engine import JaxShardedInferenceEngine
from xotorch_support_jetson_tpu.models.config import tiny_test_config
from xotorch_support_jetson_tpu.models.decoder import full_model_params


@pytest.mark.asyncio
async def test_engine_local_mesh_matches_single_device():
  cfg = tiny_test_config(n_layers=2)
  params, shard = full_model_params(jax.random.PRNGKey(5), cfg, "m")
  tokens = np.array([[3, 14, 15, 92]], dtype=np.int32)

  with jax.default_matmul_precision("highest"):
    plain = JaxShardedInferenceEngine(use_local_mesh=False)
    plain.load_test_model(shard, cfg, params)
    ref_logits, ref_state = await plain.infer_tensor("a", shard, tokens)

    meshed = JaxShardedInferenceEngine(use_local_mesh=True)
    meshed.load_test_model(shard, cfg, params)
    meshed._maybe_shard_over_local_mesh()
    assert meshed.mesh is not None and meshed.mesh.shape["tp"] == 4  # 4 q heads
    mesh_logits, mesh_state = await meshed.infer_tensor("a", shard, tokens)

    np.testing.assert_allclose(mesh_logits, ref_logits, rtol=2e-4, atol=2e-4)

    # One decode step on both paths.
    nxt = np.argmax(ref_logits, axis=-1).astype(np.int32).reshape(1, 1)
    ref2, _ = await plain.infer_tensor("a", shard, nxt, ref_state)
    mesh2, _ = await meshed.infer_tensor("a", shard, nxt, mesh_state)
    np.testing.assert_allclose(mesh2, ref2, rtol=2e-4, atol=2e-4)
