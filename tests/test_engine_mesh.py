"""Engine in-slice TP: sharded-over-mesh engine must match single-device."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from xotorch_support_jetson_tpu.inference.jax_engine import JaxShardedInferenceEngine
from xotorch_support_jetson_tpu.models.config import tiny_test_config
from xotorch_support_jetson_tpu.models.decoder import full_model_params


@pytest.mark.asyncio
async def test_engine_local_mesh_matches_single_device():
  cfg = tiny_test_config(n_layers=2)
  params, shard = full_model_params(jax.random.PRNGKey(5), cfg, "m")
  tokens = np.array([[3, 14, 15, 92]], dtype=np.int32)

  with jax.default_matmul_precision("highest"):
    plain = JaxShardedInferenceEngine(use_local_mesh=False)
    plain.load_test_model(shard, cfg, params)
    ref_logits, ref_state = await plain.infer_tensor("a", shard, tokens)

    meshed = JaxShardedInferenceEngine(use_local_mesh=True)
    meshed.load_test_model(shard, cfg, params)
    meshed._maybe_shard_over_local_mesh()
    assert meshed.mesh is not None and meshed.mesh.shape["tp"] == 4  # 4 q heads
    mesh_logits, mesh_state = await meshed.infer_tensor("a", shard, tokens)

    np.testing.assert_allclose(mesh_logits, ref_logits, rtol=2e-4, atol=2e-4)

    # One decode step on both paths.
    nxt = np.argmax(ref_logits, axis=-1).astype(np.int32).reshape(1, 1)
    ref2, _ = await plain.infer_tensor("a", shard, nxt, ref_state)
    mesh2, _ = await meshed.infer_tensor("a", shard, nxt, mesh_state)
    np.testing.assert_allclose(mesh2, ref2, rtol=2e-4, atol=2e-4)


@pytest.mark.asyncio
async def test_engine_local_mesh_moe_ep_sharding_matches():
  """MoE model through the serving mesh: the plan splits chips ep x tp,
  expert weights shard over ep (GSPMD all-to-alls), and logits match the
  single-device engine."""
  cfg = tiny_test_config(
    n_layers=2, n_experts=4, n_active_experts=2, moe_hidden_dim=32,
    shared_expert_dim=32, first_k_dense=1,
  )
  params, shard = full_model_params(jax.random.PRNGKey(9), cfg, "moe-mesh")
  tokens = np.array([[3, 14, 15, 92]], dtype=np.int32)

  with jax.default_matmul_precision("highest"):
    plain = JaxShardedInferenceEngine(use_local_mesh=False)
    plain.load_test_model(shard, cfg, params)
    ref_logits, ref_state = await plain.infer_tensor("a", shard, tokens)

    meshed = JaxShardedInferenceEngine(use_local_mesh=True)
    meshed.load_test_model(shard, cfg, params)
    meshed._maybe_shard_over_local_mesh()
    assert meshed.mesh is not None
    assert meshed.mesh.shape["ep"] == 4  # 4 experts -> ep=4 on 8 devices
    assert meshed.mesh.shape["tp"] == 2
    mesh_logits, mesh_state = await meshed.infer_tensor("a", shard, tokens)
    np.testing.assert_allclose(mesh_logits, ref_logits, rtol=2e-4, atol=2e-4)

    nxt = np.argmax(ref_logits, axis=-1).astype(np.int32).reshape(1, 1)
    ref2, _ = await plain.infer_tensor("a", shard, nxt, ref_state)
    mesh2, _ = await meshed.infer_tensor("a", shard, nxt, mesh_state)
    np.testing.assert_allclose(mesh2, ref2, rtol=2e-4, atol=2e-4)


def test_inference_plan_ep_requires_expert_divisibility():
  """A 60-expert model must not get ep=8 (60 % 8 != 0 would crash
  device_put); the plan backs off to the largest dividing power of 2."""
  from xotorch_support_jetson_tpu.parallel.mesh import inference_plan, pow2_degree

  plan = inference_plan(8, n_heads=16, n_experts=60)
  assert plan.ep == 4 and 60 % plan.ep == 0
  assert plan.tp == 2 and plan.n_devices <= 8
  assert inference_plan(8, n_heads=16, n_experts=64).ep == 8
  assert inference_plan(8, n_heads=16, n_experts=0).ep == 1
  assert pow2_degree(8, 3) == 2  # limit caps below device count
  assert pow2_degree(6, 16) == 2  # degree must divide the device count


def test_batched_decode_over_local_mesh_matches():
  """The pooled batch-decode path with GSPMD-sharded params (use_local_mesh
  TP) == the unsharded pool: the batched server composes with in-slice TP."""
  from xotorch_support_jetson_tpu.models.decoder import fused_batch_decode, init_kv_cache, prefill_into_slot
  from xotorch_support_jetson_tpu.parallel.mesh import build_mesh, inference_plan, shard_params

  cfg = tiny_test_config(n_layers=2, max_seq_len=128)
  params, shard = full_model_params(jax.random.PRNGKey(12), cfg, "m")
  mesh = build_mesh(inference_plan(8, n_heads=cfg.n_heads))
  sharded = shard_params(jax.tree.map(jnp.copy, params), mesh)

  prompts = [[3, 25, 9], [7, 1, 88, 42, 5]]
  outs = []
  with jax.default_matmul_precision("highest"):
    for p in (params, sharded):
      cache = init_kv_cache(cfg, cfg.n_layers, 2, 64)
      firsts = []
      for r, prompt in enumerate(prompts):
        pad = np.zeros((1, 16), np.int32)
        pad[0, : len(prompt)] = prompt
        last, cache = prefill_into_slot(p, cfg, shard, jnp.asarray(pad), cache, jnp.int32(r), jnp.int32(len(prompt)))
        firsts.append(int(np.argmax(np.asarray(last)[0])))
      tok = jnp.asarray([[f] for f in firsts], jnp.int32)
      pos = jnp.asarray([len(x) for x in prompts], jnp.int32)
      act = jnp.ones((2,), bool)
      temps = jnp.zeros((2,), jnp.float32)
      toks, _, _, _ = fused_batch_decode(p, cfg, shard, tok, cache, pos, act, temps, 10)
      outs.append((firsts, np.asarray(toks)))
  assert outs[0][0] == outs[1][0]
  assert np.array_equal(outs[0][1], outs[1][1])
