"""int8 weight quantization: fidelity, engine integration, mesh sharding."""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from xotorch_support_jetson_tpu.models.config import tiny_test_config
from xotorch_support_jetson_tpu.models.decoder import full_model_params, fused_decode, init_kv_cache, jit_shard_forward
from xotorch_support_jetson_tpu.models.quantize import qdot, quantize_params, quantize_weight


def _logits(params, cfg, shard, tokens):
  positions = jnp.broadcast_to(jnp.arange(tokens.shape[1], dtype=jnp.int32), tokens.shape)
  cache = init_kv_cache(cfg, shard.n_shard_layers, tokens.shape[0], 32)
  out, _ = jit_shard_forward(params, cfg, shard, tokens, positions, cache)
  return np.asarray(out[:, -1, :], dtype=np.float32)


def test_quantize_weight_roundtrip_error():
  w = jax.random.normal(jax.random.PRNGKey(0), (64, 128), jnp.float32)
  q, s = quantize_weight(w)
  assert q.dtype == jnp.int8 and s.shape == (128,)
  deq = q.astype(jnp.float32) * s[None, :]
  # Symmetric int8 per-channel: max error is half a quantization step.
  step = np.asarray(s)[None, :]
  assert np.max(np.abs(np.asarray(deq - w))) <= 0.5 * step.max() + 1e-6


def test_qdot_modes_close():
  x = jax.random.normal(jax.random.PRNGKey(1), (4, 64), jnp.float32)
  w = jax.random.normal(jax.random.PRNGKey(2), (64, 32), jnp.float32)
  q, s = quantize_weight(w)
  ref = np.asarray(x @ w)
  w8a16 = np.asarray(qdot(x, q, s, "w8a16"))
  w8a8 = np.asarray(qdot(x, q, s, "w8a8"))
  # ~1% relative error on random gaussians is the expected int8 regime.
  assert np.abs(w8a16 - ref).max() / np.abs(ref).max() < 0.02
  assert np.abs(w8a8 - ref).max() / np.abs(ref).max() < 0.03


def test_quantized_model_logits_track_full_precision():
  cfg = tiny_test_config(n_layers=2)
  params, shard = full_model_params(jax.random.PRNGKey(3), cfg, "m")
  qparams = quantize_params(params)
  # Layer weights went int8 with sibling scales; norms/embed untouched.
  assert qparams["layers"]["wq"].dtype == jnp.int8
  assert "wq_scale" in qparams["layers"]
  assert qparams["layers"]["attn_norm"].dtype == params["layers"]["attn_norm"].dtype
  assert qparams["lm_head"].dtype == jnp.int8
  assert qparams["embed"].dtype == params["embed"].dtype

  # Tied-embedding variant grows an int8 lm_head copy; the full-precision
  # table is kept for the embedding gather.
  tied_cfg = tiny_test_config(n_layers=2, tied_embedding=True)
  tied_params, _ = full_model_params(jax.random.PRNGKey(8), tied_cfg, "m")
  tied_q = quantize_params(tied_params)
  assert "lm_head" not in tied_params and tied_q["lm_head"].dtype == jnp.int8
  assert tied_q["embed"].dtype == tied_params["embed"].dtype

  tokens = jnp.asarray([[3, 1, 4, 1, 5]], dtype=jnp.int32)
  ref = _logits(params, cfg, shard, tokens)
  quant = _logits(qparams, cfg, shard, tokens)
  # Quantized logits must rank the same argmax and correlate strongly.
  assert np.argmax(ref) == np.argmax(quant)
  cos = float(np.dot(ref.ravel(), quant.ravel()) / (np.linalg.norm(ref) * np.linalg.norm(quant)))
  assert cos > 0.995, cos


def test_quantized_fused_decode_runs_greedy():
  cfg = tiny_test_config(n_layers=2)
  params, shard = full_model_params(jax.random.PRNGKey(4), cfg, "m")
  qparams = quantize_params(params)
  cache = init_kv_cache(cfg, shard.n_shard_layers, 1, 32)
  toks, _ = fused_decode(qparams, cfg, shard, jnp.asarray([[7]], jnp.int32), cache, jnp.zeros((1,), jnp.int32), 6, temp=0.0)
  toks2_cache = init_kv_cache(cfg, shard.n_shard_layers, 1, 32)
  toks2, _ = fused_decode(qparams, cfg, shard, jnp.asarray([[7]], jnp.int32), toks2_cache, jnp.zeros((1,), jnp.int32), 6, temp=0.0)
  np.testing.assert_array_equal(np.asarray(toks), np.asarray(toks2))


@pytest.mark.asyncio
async def test_engine_quant_mode():
  from xotorch_support_jetson_tpu.inference.jax_engine import JaxShardedInferenceEngine

  cfg = tiny_test_config(n_layers=2)
  params, shard = full_model_params(jax.random.PRNGKey(5), cfg, "m")
  engine = JaxShardedInferenceEngine(quant="int8")
  engine.load_test_model(shard, cfg, quantize_params(params))
  tokens = np.array([[2, 9, 6]], dtype=np.int32)
  logits, _ = await engine.infer_tensor("r", shard, tokens)
  assert logits.shape == (1, cfg.vocab_size)
  full = JaxShardedInferenceEngine()
  full.load_test_model(shard, cfg, params)
  ref, _ = await full.infer_tensor("r", shard, tokens)
  assert np.argmax(ref) == np.argmax(logits)


def test_quantized_params_shard_over_mesh():
  from xotorch_support_jetson_tpu.parallel.mesh import MeshPlan, build_mesh, shard_params

  cfg = tiny_test_config(n_layers=2, n_heads=4, n_kv_heads=2)
  params, shard = full_model_params(jax.random.PRNGKey(6), cfg, "m")
  qparams = quantize_params(params)
  mesh = build_mesh(MeshPlan(tp=2), jax.devices()[:2])
  sharded = shard_params(qparams, mesh)
  # Scales land sharded on the same axis as their weight's output dim.
  assert sharded["layers"]["wq_scale"].sharding.spec == jax.sharding.PartitionSpec(None, "tp")
