"""int8 weight quantization: fidelity, engine integration, mesh sharding."""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from xotorch_support_jetson_tpu.models.config import tiny_test_config
from xotorch_support_jetson_tpu.models.decoder import full_model_params, fused_decode, init_kv_cache, jit_shard_forward
from xotorch_support_jetson_tpu.models.quantize import qdot, quantize_params, quantize_weight


def _logits(params, cfg, shard, tokens):
  positions = jnp.broadcast_to(jnp.arange(tokens.shape[1], dtype=jnp.int32), tokens.shape)
  cache = init_kv_cache(cfg, shard.n_shard_layers, tokens.shape[0], 32)
  out, _ = jit_shard_forward(params, cfg, shard, tokens, positions, cache)
  return np.asarray(out[:, -1, :], dtype=np.float32)


def test_quantize_weight_roundtrip_error():
  w = jax.random.normal(jax.random.PRNGKey(0), (64, 128), jnp.float32)
  q, s = quantize_weight(w)
  assert q.dtype == jnp.int8 and s.shape == (128,)
  deq = q.astype(jnp.float32) * s[None, :]
  # Symmetric int8 per-channel: max error is half a quantization step.
  step = np.asarray(s)[None, :]
  assert np.max(np.abs(np.asarray(deq - w))) <= 0.5 * step.max() + 1e-6


def test_qdot_modes_close():
  x = jax.random.normal(jax.random.PRNGKey(1), (4, 64), jnp.float32)
  w = jax.random.normal(jax.random.PRNGKey(2), (64, 32), jnp.float32)
  q, s = quantize_weight(w)
  ref = np.asarray(x @ w)
  w8a16 = np.asarray(qdot(x, q, s, "w8a16"))
  w8a8 = np.asarray(qdot(x, q, s, "w8a8"))
  # ~1% relative error on random gaussians is the expected int8 regime.
  assert np.abs(w8a16 - ref).max() / np.abs(ref).max() < 0.02
  assert np.abs(w8a8 - ref).max() / np.abs(ref).max() < 0.03


def test_quantized_model_logits_track_full_precision():
  cfg = tiny_test_config(n_layers=2)
  params, shard = full_model_params(jax.random.PRNGKey(3), cfg, "m")
  qparams = quantize_params(params)
  # Layer weights went int8 with sibling scales; norms/embed untouched.
  assert qparams["layers"]["wq"].dtype == jnp.int8
  assert "wq_scale" in qparams["layers"]
  assert qparams["layers"]["attn_norm"].dtype == params["layers"]["attn_norm"].dtype
  assert qparams["lm_head"].dtype == jnp.int8
  assert qparams["embed"].dtype == params["embed"].dtype

  # Tied-embedding variant grows an int8 lm_head copy; the full-precision
  # table is kept for the embedding gather.
  tied_cfg = tiny_test_config(n_layers=2, tied_embedding=True)
  tied_params, _ = full_model_params(jax.random.PRNGKey(8), tied_cfg, "m")
  tied_q = quantize_params(tied_params)
  assert "lm_head" not in tied_params and tied_q["lm_head"].dtype == jnp.int8
  assert tied_q["embed"].dtype == tied_params["embed"].dtype

  tokens = jnp.asarray([[3, 1, 4, 1, 5]], dtype=jnp.int32)
  ref = _logits(params, cfg, shard, tokens)
  quant = _logits(qparams, cfg, shard, tokens)
  # Quantized logits must rank the same argmax and correlate strongly.
  assert np.argmax(ref) == np.argmax(quant)
  cos = float(np.dot(ref.ravel(), quant.ravel()) / (np.linalg.norm(ref) * np.linalg.norm(quant)))
  assert cos > 0.995, cos


def test_quantized_fused_decode_runs_greedy():
  cfg = tiny_test_config(n_layers=2)
  params, shard = full_model_params(jax.random.PRNGKey(4), cfg, "m")
  qparams = quantize_params(params)
  cache = init_kv_cache(cfg, shard.n_shard_layers, 1, 32)
  toks, _ = fused_decode(qparams, cfg, shard, jnp.asarray([[7]], jnp.int32), cache, jnp.zeros((1,), jnp.int32), 6, temp=0.0)
  toks2_cache = init_kv_cache(cfg, shard.n_shard_layers, 1, 32)
  toks2, _ = fused_decode(qparams, cfg, shard, jnp.asarray([[7]], jnp.int32), toks2_cache, jnp.zeros((1,), jnp.int32), 6, temp=0.0)
  np.testing.assert_array_equal(np.asarray(toks), np.asarray(toks2))


@pytest.mark.asyncio
async def test_engine_quant_mode():
  from xotorch_support_jetson_tpu.inference.jax_engine import JaxShardedInferenceEngine

  cfg = tiny_test_config(n_layers=2)
  params, shard = full_model_params(jax.random.PRNGKey(5), cfg, "m")
  engine = JaxShardedInferenceEngine(quant="int8")
  engine.load_test_model(shard, cfg, quantize_params(params))
  tokens = np.array([[2, 9, 6]], dtype=np.int32)
  logits, _ = await engine.infer_tensor("r", shard, tokens)
  assert logits.shape == (1, cfg.vocab_size)
  full = JaxShardedInferenceEngine()
  full.load_test_model(shard, cfg, params)
  ref, _ = await full.infer_tensor("r", shard, tokens)
  assert np.argmax(ref) == np.argmax(logits)


def test_quantized_params_shard_over_mesh():
  from xotorch_support_jetson_tpu.parallel.mesh import MeshPlan, build_mesh, shard_params

  cfg = tiny_test_config(n_layers=2, n_heads=4, n_kv_heads=2)
  params, shard = full_model_params(jax.random.PRNGKey(6), cfg, "m")
  qparams = quantize_params(params)
  mesh = build_mesh(MeshPlan(tp=2), jax.devices()[:2])
  sharded = shard_params(qparams, mesh)
  # Scales land sharded on the same axis as their weight's output dim.
  assert sharded["layers"]["wq_scale"].sharding.spec == jax.sharding.PartitionSpec(None, "tp")


# ------------------------------------------------------------------- int4


def test_int4_pack_unpack_roundtrip():
  from xotorch_support_jetson_tpu.models.quantize import quantize_weight_int4, unpack_int4

  w = jax.random.normal(jax.random.PRNGKey(11), (64, 128), jnp.float32)
  packed, s = quantize_weight_int4(w)
  assert packed.dtype == jnp.int8 and packed.shape == (32, 128) and s.shape == (128,)
  q = np.asarray(unpack_int4(packed))
  assert q.min() >= -8 and q.max() <= 7
  deq = q.astype(np.float32) * np.asarray(s)[None, :]
  # symmetric int4: max error is half a step (absmax/7)
  assert np.max(np.abs(deq - np.asarray(w))) <= 0.5 * np.asarray(s).max() + 1e-6


def test_qdot_int4_close():
  from xotorch_support_jetson_tpu.models.quantize import quantize_weight_int4

  x = jax.random.normal(jax.random.PRNGKey(12), (4, 64), jnp.float32)
  w = jax.random.normal(jax.random.PRNGKey(13), (64, 32), jnp.float32)
  packed, s = quantize_weight_int4(w)
  # qdot must equal x @ dequantized(w) EXACTLY (it's the same computation)
  from xotorch_support_jetson_tpu.models.quantize import unpack_int4

  deq = np.asarray(unpack_int4(packed)).astype(np.float32) * np.asarray(s)[None, :]
  got = np.asarray(qdot(x, packed, s))
  np.testing.assert_allclose(got, np.asarray(x) @ deq, rtol=1e-5, atol=1e-5)
  # and sit in the expected 4-bit error regime vs full precision
  ref = np.asarray(x @ w)
  assert np.abs(got - ref).max() / np.abs(ref).max() < 0.25


def test_int4_model_generates_and_tracks_full_precision():
  """XOT_TPU_QUANT=int4 tree: packed leaves, halved bytes, greedy decode
  runs end-to-end; with weights PRE-SNAPPED to the int4 grid the quantized
  model is numerically exact vs full precision (token-identical decode)."""
  from xotorch_support_jetson_tpu.models.quantize import quantize_weight_int4, unpack_int4

  cfg = tiny_test_config(n_layers=2)
  params, shard = full_model_params(jax.random.PRNGKey(14), cfg, "m")

  # Snap every eligible leaf exactly onto its int4 grid first.
  from xotorch_support_jetson_tpu.models.quantize import QUANT_STACK_LEAVES

  snapped = dict(params)
  layers = dict(params["layers"])
  for name in QUANT_STACK_LEAVES["layers"]:
    if name in layers:
      p4, s4 = quantize_weight_int4(layers[name])
      layers[name] = (unpack_int4(p4).astype(jnp.float32) * s4[..., None, :]).astype(layers[name].dtype)
  snapped["layers"] = layers
  if "lm_head" in snapped:
    p4, s4 = quantize_weight_int4(snapped["lm_head"])
    snapped["lm_head"] = (unpack_int4(p4).astype(jnp.float32) * s4[None, :]).astype(snapped["lm_head"].dtype)

  q = quantize_params(snapped, "int4")
  assert q["layers"]["wq"].dtype == jnp.int8
  assert q["layers"]["wq"].shape[-2] * 2 == snapped["layers"]["wq"].shape[-2]
  assert "wq_scale" in q["layers"]

  toks = jnp.asarray([[3, 25, 9]], dtype=jnp.int32)
  full = _logits(snapped, cfg, shard, toks)
  quant = _logits(q, cfg, shard, toks)
  np.testing.assert_allclose(quant, full, rtol=2e-4, atol=2e-4)

  # greedy decode end-to-end (the serving path) — token identical
  pos = jnp.broadcast_to(jnp.arange(3, dtype=jnp.int32), (1, 3))
  for tree in (snapped, q):
    cache = init_kv_cache(cfg, shard.n_shard_layers, 1, 32)
    logits, cache = jit_shard_forward(tree, cfg, shard, toks, pos, cache)
    first = jnp.argmax(logits[:, -1, :], axis=-1).astype(jnp.int32)[:, None]
    out, _ = fused_decode(tree, cfg, shard, first, cache, jnp.full((1,), 3, jnp.int32), 6, temp=0.0)
    if tree is snapped:
      want = np.asarray(out)
    else:
      np.testing.assert_array_equal(np.asarray(out), want)


def test_int4_odd_indim_leaf_stays_full_precision():
  """Leaves whose in-dim can't pack (odd) are skipped, not corrupted."""
  cfg = tiny_test_config(
    n_layers=2,
    kv_lora_rank=17,  # odd: wkv_b in-dim can't pack
    qk_nope_head_dim=8,
    qk_rope_head_dim=4,
    v_head_dim=8,
  )
  params, shard = full_model_params(jax.random.PRNGKey(15), cfg, "m")
  q = quantize_params(params, "int4")
  assert q["layers"]["wkv_b"].dtype != jnp.int8  # skipped (odd rank)
  assert q["layers"]["wo"].dtype == jnp.int8  # H*v_head_dim even: packed
  toks = jnp.asarray([[3, 25, 9]], dtype=jnp.int32)
  out = _logits(q, cfg, shard, toks)
  assert np.isfinite(out).all()


def test_int4_mla_absorbed_path():
  """Even-rank MLA under int4: wkv_b packs, and the weight-absorption site
  (decoder._mla_w_kv_b -> dequantize_leaf) detects + unpacks it."""
  cfg = tiny_test_config(
    n_layers=2,
    kv_lora_rank=16,
    qk_nope_head_dim=8,
    qk_rope_head_dim=4,
    v_head_dim=8,
  )
  params, shard = full_model_params(jax.random.PRNGKey(16), cfg, "m")
  q = quantize_params(params, "int4")
  assert q["layers"]["wkv_b"].dtype == jnp.int8
  assert q["layers"]["wkv_b"].shape[-2] * 2 == params["layers"]["wkv_b"].shape[-2]
  toks = jnp.asarray([[3, 25, 9]], dtype=jnp.int32)
  out = _logits(q, cfg, shard, toks)
  full = _logits(params, cfg, shard, toks)
  assert np.isfinite(out).all()
  # int4 on random weights: coarse but correlated with full precision
  assert np.corrcoef(out.ravel(), full.ravel())[0, 1] > 0.9


def test_int4_moe_expert_path():
  """int4 expert stacks: gate/up pack along D, down along moe_hidden — the
  dequant site (decoder._mlp_block expert_w) must pick the right in_dim for
  each, and the routed forward must track full precision."""
  cfg = tiny_test_config(n_layers=2, n_experts=4, n_active_experts=2, moe_hidden_dim=32)
  params, shard = full_model_params(jax.random.PRNGKey(17), cfg, "m")
  q = quantize_params(params, "int4")
  lay = q["moe_layers"] if "moe_layers" in q else q["layers"]
  full_lay = params["moe_layers"] if "moe_layers" in params else params["layers"]
  assert lay["w_experts_gate"].shape[-2] * 2 == full_lay["w_experts_gate"].shape[-2]
  assert lay["w_experts_down"].shape[-2] * 2 == full_lay["w_experts_down"].shape[-2]
  toks = jnp.asarray([[3, 25, 9, 7]], dtype=jnp.int32)
  out = _logits(q, cfg, shard, toks)
  full = _logits(params, cfg, shard, toks)
  assert np.isfinite(out).all()
  assert np.corrcoef(out.ravel(), full.ravel())[0, 1] > 0.9


def test_int4_kernel_matches_two_dot_reference():
  """The in-register-unpack Pallas matmul (ops/pallas_int4.py, interpret
  mode on CPU) must match the shipped two-dot qdot formulation on the same
  packed weights — identical math, single HBM read."""
  import numpy as np

  from xotorch_support_jetson_tpu.models.quantize import qdot, quantize_weight_int4
  from xotorch_support_jetson_tpu.ops.pallas_int4 import BLOCK_IN, BLOCK_OUT, int4_matmul

  key = jax.random.PRNGKey(0)
  T, d_in, d_out = 4, BLOCK_IN * 2, BLOCK_OUT
  w = jax.random.normal(key, (d_in, d_out), jnp.float32) * 0.05
  packed, scale = quantize_weight_int4(w)
  x = jax.random.normal(jax.random.fold_in(key, 1), (T, d_in), jnp.float32)

  want = qdot(x, packed, scale)  # two-dot reference
  got = int4_matmul(x, packed, scale, interpret=True)
  np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=2e-2, atol=2e-2)
