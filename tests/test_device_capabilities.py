"""Device capability probes (topology/device_capabilities.py).

The heterogeneous parsing helpers are pure functions (reference parity:
``device_capabilities.py:166-384`` probes Apple/CUDA/Jetson) — tested here
without the hardware; the live probe path is exercised for whatever this CI
host actually is (CPU or TPU)."""

from xotorch_support_jetson_tpu.topology.device_capabilities import (
  DeviceCapabilities,
  apple_caps_from,
  cuda_caps_from,
  device_capabilities_sync,
  jetson_caps_from,
)


def test_cuda_caps_lookup_and_scaling():
  caps = cuda_caps_from("NVIDIA GeForce RTX 4090", 24 * 1024**3, n_devices=2)
  assert caps.memory == 2 * 24 * 1024
  assert caps.flops.fp16 == 2 * 165.2
  assert "2x" in caps.model
  unknown = cuda_caps_from("NVIDIA Mystery GPU", 8 * 1024**3)
  assert unknown.flops.fp16 == 0 and unknown.memory == 8 * 1024


def test_jetson_caps_from_meminfo():
  meminfo = "MemTotal:       32412345 kB\nMemFree:        100 kB\n"
  caps = jetson_caps_from("Jetson AGX Orin Developer Kit", meminfo)
  assert caps.memory == 32412345 // 1024
  assert caps.flops.int8 == 170.0  # matched "jetson agx orin"


def test_apple_caps_lookup_prefers_most_specific():
  pro = apple_caps_from("Apple M2 Pro", 16 * 1024)
  base = apple_caps_from("Apple M2", 8 * 1024)
  assert pro.flops.fp16 == 13.6 and base.flops.fp16 == 7.2  # "m2 pro" != "m2"


def test_live_probe_returns_something_sane():
  caps = device_capabilities_sync()
  assert isinstance(caps, DeviceCapabilities)
  assert caps.memory > 0
  assert caps.chip
  # Round-trips through the wire dict format.
  assert DeviceCapabilities.from_dict(caps.to_dict()).memory == caps.memory
