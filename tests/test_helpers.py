import asyncio

import pytest

from xotorch_support_jetson_tpu.utils.helpers import (
  AsyncCallbackSystem,
  PrefixDict,
  find_available_port,
  get_or_create_node_id,
  pretty_print_bytes,
)


@pytest.mark.asyncio
async def test_callback_wait_and_trigger():
  system: AsyncCallbackSystem[str, int] = AsyncCallbackSystem()
  cb = system.register("req1")
  seen = []
  cb.on_next(lambda v: seen.append(v))

  async def fire():
    await asyncio.sleep(0.01)
    system.trigger("req1", 41)
    await asyncio.sleep(0.01)
    system.trigger("req1", 42)

  task = asyncio.create_task(fire())
  result = await cb.wait(lambda v: v == 42, timeout=2)
  await task
  assert result == (42,)
  assert seen == [41, 42]


@pytest.mark.asyncio
async def test_callback_wait_timeout():
  system: AsyncCallbackSystem[str, int] = AsyncCallbackSystem()
  cb = system.register("req")
  with pytest.raises(asyncio.TimeoutError):
    await cb.wait(lambda v: True, timeout=0.05)


@pytest.mark.asyncio
async def test_trigger_all():
  system: AsyncCallbackSystem[str, str] = AsyncCallbackSystem()
  a, b = system.register("a"), system.register("b")
  system.trigger_all("x")
  assert a.result == ("x",) and b.result == ("x",)
  system.deregister("a")
  system.trigger("a", "y")  # no-op, no raise


def test_prefix_dict():
  d: PrefixDict[str, int] = PrefixDict()
  d["chatcmpl-abc"] = 1
  d["chatcmpl-abcdef"] = 2
  assert d.find_longest_prefix("chatcmpl-abcdef-xyz") == ("chatcmpl-abcdef", 2)
  assert len(d.items_with_prefix("chatcmpl-")) == 2


def test_find_available_port_binds():
  import socket

  port = find_available_port("127.0.0.1")
  with socket.socket(socket.AF_INET, socket.SOCK_STREAM) as s:
    s.bind(("127.0.0.1", port))


def test_node_id_env_override():
  assert get_or_create_node_id() == "test-node-id"


def test_pretty_bytes():
  assert pretty_print_bytes(512) == "512 B"
  assert pretty_print_bytes(2048) == "2.00 KB"
  assert pretty_print_bytes(3 * 1024**3) == "3.00 GB"
