"""Node orchestration tests with the dummy engine (no network, no model)."""

import asyncio

import numpy as np
import pytest

from xotorch_support_jetson_tpu.inference.dummy_engine import DUMMY_EOS, DummyInferenceEngine
from xotorch_support_jetson_tpu.networking.discovery import Discovery
from xotorch_support_jetson_tpu.orchestration.node import Node
from xotorch_support_jetson_tpu.registry import build_base_shard
from xotorch_support_jetson_tpu.topology.partitioning import RingMemoryWeightedPartitioningStrategy


class NoDiscovery(Discovery):
  async def start(self):
    pass

  async def stop(self):
    pass

  async def discover_peers(self, wait_for_peers: int = 0):
    return []


class StubServer:
  async def start(self):
    pass

  async def stop(self):
    pass


def make_node(node_id="n1", max_tokens=200):
  return Node(
    node_id,
    StubServer(),
    DummyInferenceEngine(),
    NoDiscovery(),
    None,
    RingMemoryWeightedPartitioningStrategy(),
    max_generate_tokens=max_tokens,
  )


@pytest.mark.asyncio
async def test_single_node_generates_until_eos():
  node = make_node()
  await node.start()
  shard = build_base_shard("dummy", "DummyInferenceEngine")
  request_id = "req-1"
  callback = node.on_token.register("test")
  done = asyncio.Event()
  collected = []

  def on_tok(rid, tokens, finished):
    if rid == request_id:
      collected.extend(tokens)
      if finished:
        done.set()

  callback.on_next(on_tok)
  # Dummy engine: last-layer output = input + 1, sample takes the last value,
  # so tokens count up deterministically until EOS (69).
  await node.process_prompt(shard, "aaaa", request_id)  # one word, len 4 → token 5
  await asyncio.wait_for(done.wait(), timeout=10)
  assert collected[-1] == DUMMY_EOS
  assert collected == list(range(5, DUMMY_EOS + 1))
  tokens, finished = node.buffered_token_output[request_id]
  assert finished and tokens[-1] == DUMMY_EOS
  await node.stop()


@pytest.mark.asyncio
async def test_single_node_max_tokens_cutoff():
  node = make_node(max_tokens=5)
  await node.start()
  shard = build_base_shard("dummy", "DummyInferenceEngine")
  done = asyncio.Event()
  node.on_token.register("t").on_next(lambda rid, toks, fin: done.set() if fin else None)
  await node.process_prompt(shard, "a", "req-2")
  await asyncio.wait_for(done.wait(), timeout=10)
  tokens, finished = node.buffered_token_output["req-2"]
  assert finished and len(tokens) == 5
  await node.stop()


@pytest.mark.asyncio
async def test_node_status_active_node_tracking():
  node = make_node()
  await node.start()
  assert node.topology.active_node_id in (node.id, None)
  node.on_node_status("r", '{"type": "node_status", "status": "start_process_prompt", "node_id": "other"}')
  assert node.topology.active_node_id == "other"
  node.on_node_status("r", '{"type": "node_status", "status": "end_process_prompt", "node_id": "other"}')
  assert node.topology.active_node_id is None
  await node.stop()


@pytest.mark.asyncio
async def test_single_node_training_step():
  node = make_node()
  await node.start()
  shard = build_base_shard("dummy", "DummyInferenceEngine")
  # Dummy engine has no train(): NotImplementedError per the explicit contract.
  with pytest.raises(NotImplementedError):
    await node.process_example(shard, np.ones((1, 4), np.int32), np.ones((1, 4), np.int32), np.array([4]), True, "r")
  await node.stop()


class _StubTokenizer:
  """Minimal tokenizer: maps chars to small ids; eos configurable."""

  def __init__(self, eos_token_id: int):
    self.eos_token_id = eos_token_id

  def encode(self, text: str):
    return [(ord(c) % 50) + 1 for c in text][:8]

  def decode(self, ids):
    return " ".join(str(i) for i in ids)


@pytest.mark.asyncio
async def test_node_oneshot_nonstreaming_matches_chunked():
  """A non-streaming request (API hint stream=False) takes the one-dispatch
  fused_generate path and must produce the same tokens as the default
  chunked path."""
  import jax

  from xotorch_support_jetson_tpu.inference.jax_engine import JaxShardedInferenceEngine
  from xotorch_support_jetson_tpu.inference.shard import Shard
  from xotorch_support_jetson_tpu.models.config import tiny_test_config
  from xotorch_support_jetson_tpu.models.decoder import full_model_params

  cfg = tiny_test_config(n_layers=2)
  params, shard = full_model_params(jax.random.PRNGKey(7), cfg, "m")

  async def run(stream_hint):
    engine = JaxShardedInferenceEngine()
    engine.load_test_model(shard, cfg, params, tokenizer=_StubTokenizer(eos_token_id=-1))
    node = Node("n1", StubServer(), engine, NoDiscovery(), None, RingMemoryWeightedPartitioningStrategy(), max_generate_tokens=200, default_sample_temp=0.0)
    await node.start()
    done = asyncio.Event()
    collected = []

    def on_tok(rid, toks, fin):
      collected.extend(toks)
      if fin:
        done.set()

    node.on_token.register("t").on_next(on_tok)
    rid = "req-os"
    node.set_request_options(rid, stream=stream_hint, max_tokens=9, temperature=0.0)
    await node.process_prompt(Shard("m", 0, cfg.n_layers - 1, cfg.n_layers), "hello", rid)
    await asyncio.wait_for(done.wait(), timeout=30)
    await node.stop()
    return collected

  chunked = await run(True)
  oneshot = await run(False)
  assert len(chunked) == 9
  assert oneshot == chunked


@pytest.mark.asyncio
async def test_retry_request_replays_token_history(monkeypatch):
  """Elastic in-flight recovery (reference fails these — SURVEY §5.3):
  a dead next-hop triggers a replay of the full token history as a fresh
  prefill with the restart flag; attempts are bounded."""
  import numpy as np

  from xotorch_support_jetson_tpu.inference.state import InferenceState

  monkeypatch.setenv("XOT_TPU_RETRY_DELAY_S", "0")
  node = make_node()
  await node.start()
  shard = build_base_shard("dummy", "DummyInferenceEngine")

  forwarded = []

  async def fake_forward_tensor(base_shard, tensor, request_id, target_index, inference_state=None):
    forwarded.append((np.asarray(tensor).copy(), inference_state))

  node.forward_tensor = fake_forward_tensor
  state = InferenceState(tokens=np.asarray([[5, 6, 7, 8]], np.int32), prompt_len=2)
  await node._retry_request(shard, "rid-replay", state)

  assert len(forwarded) == 1
  tensor, replay_state = forwarded[0]
  assert tensor.tolist() == [[5, 6, 7, 8]]  # prompt + generated so far
  assert replay_state.extras.get("replay_epoch") == 1
  assert replay_state.prompt_len == 4
  assert node._replay_attempts["rid-replay"] == 1

  # Exhaustion: after the retry budget the request finishes (with an event).
  node._replay_attempts["rid-replay"] = 99
  finished = []
  node.on_token.register("t").on_next(lambda rid, toks, fin: finished.append((rid, fin)))
  await node._retry_request(shard, "rid-replay", state)
  assert ("rid-replay", True) in finished
  await node.stop()


@pytest.mark.asyncio
async def test_engine_restart_flag_resets_session():
  """The replay's restart flag makes the engine prefill from scratch even
  though a session exists for the request id."""
  import jax
  import numpy as np

  from xotorch_support_jetson_tpu.inference.jax_engine import JaxShardedInferenceEngine
  from xotorch_support_jetson_tpu.inference.state import InferenceState
  from xotorch_support_jetson_tpu.models.config import tiny_test_config
  from xotorch_support_jetson_tpu.models.decoder import full_model_params

  cfg = tiny_test_config(n_layers=2)
  params, shard = full_model_params(jax.random.PRNGKey(3), cfg, "m")
  engine = JaxShardedInferenceEngine(use_local_mesh=False)
  engine.load_test_model(shard, cfg, params)

  rid = "replay-me"
  prompt = np.asarray([[4, 9, 2]], np.int32)
  out1, st = await engine.infer_tensor(rid, shard, prompt, None)
  nxt = np.argmax(out1, axis=-1).astype(np.int32).reshape(1, 1)
  out2, st = await engine.infer_tensor(rid, shard, nxt, st)
  assert engine.sessions[rid].curr_pos == 4

  # Replay: full history with a bumped epoch ⇒ session resets, fresh prefill.
  history = np.concatenate([prompt, nxt], axis=1)
  replay = InferenceState(tokens=history.copy(), prompt_len=4, extras={"replay_epoch": 1})
  out3, _ = await engine.infer_tensor(rid, shard, history, replay)
  assert engine.sessions[rid].prompt_len == 4 and engine.sessions[rid].epoch == 1
  # The epoch is read, NOT consumed — it must keep traveling down the ring.
  assert replay.extras.get("replay_epoch") == 1
  np.testing.assert_allclose(out3, out2, rtol=2e-4, atol=2e-4)  # same logits as pre-failure
