"""Node orchestration tests with the dummy engine (no network, no model)."""

import asyncio

import numpy as np
import pytest

from xotorch_support_jetson_tpu.inference.dummy_engine import DUMMY_EOS, DummyInferenceEngine
from xotorch_support_jetson_tpu.inference.shard import Shard
from xotorch_support_jetson_tpu.networking.discovery import Discovery
from xotorch_support_jetson_tpu.orchestration.node import Node
from xotorch_support_jetson_tpu.registry import build_base_shard
from xotorch_support_jetson_tpu.topology.partitioning import RingMemoryWeightedPartitioningStrategy


class NoDiscovery(Discovery):
  async def start(self):
    pass

  async def stop(self):
    pass

  async def discover_peers(self, wait_for_peers: int = 0):
    return []


class StubServer:
  async def start(self):
    pass

  async def stop(self):
    pass


def make_node(node_id="n1", max_tokens=200):
  return Node(
    node_id,
    StubServer(),
    DummyInferenceEngine(),
    NoDiscovery(),
    None,
    RingMemoryWeightedPartitioningStrategy(),
    max_generate_tokens=max_tokens,
  )


@pytest.mark.asyncio
async def test_single_node_generates_until_eos():
  node = make_node()
  await node.start()
  shard = build_base_shard("dummy", "DummyInferenceEngine")
  request_id = "req-1"
  callback = node.on_token.register("test")
  done = asyncio.Event()
  collected = []

  def on_tok(rid, tokens, finished):
    if rid == request_id:
      collected.extend(tokens)
      if finished:
        done.set()

  callback.on_next(on_tok)
  # Dummy engine: last-layer output = input + 1, sample takes the last value,
  # so tokens count up deterministically until EOS (69).
  await node.process_prompt(shard, "aaaa", request_id)  # one word, len 4 → token 5
  await asyncio.wait_for(done.wait(), timeout=10)
  assert collected[-1] == DUMMY_EOS
  assert collected == list(range(5, DUMMY_EOS + 1))
  tokens, finished = node.buffered_token_output[request_id]
  assert finished and tokens[-1] == DUMMY_EOS
  await node.stop()


@pytest.mark.asyncio
async def test_single_node_max_tokens_cutoff():
  node = make_node(max_tokens=5)
  await node.start()
  shard = build_base_shard("dummy", "DummyInferenceEngine")
  done = asyncio.Event()
  node.on_token.register("t").on_next(lambda rid, toks, fin: done.set() if fin else None)
  await node.process_prompt(shard, "a", "req-2")
  await asyncio.wait_for(done.wait(), timeout=10)
  tokens, finished = node.buffered_token_output["req-2"]
  assert finished and len(tokens) == 5
  await node.stop()


@pytest.mark.asyncio
async def test_node_status_active_node_tracking():
  node = make_node()
  await node.start()
  assert node.topology.active_node_id in (node.id, None)
  node.on_node_status("r", '{"type": "node_status", "status": "start_process_prompt", "node_id": "other"}')
  assert node.topology.active_node_id == "other"
  node.on_node_status("r", '{"type": "node_status", "status": "end_process_prompt", "node_id": "other"}')
  assert node.topology.active_node_id is None
  await node.stop()


@pytest.mark.asyncio
async def test_single_node_training_step():
  node = make_node()
  await node.start()
  shard = build_base_shard("dummy", "DummyInferenceEngine")
  # Dummy engine has no train(): NotImplementedError per the explicit contract.
  with pytest.raises(NotImplementedError):
    await node.process_example(shard, np.ones((1, 4), np.int32), np.ones((1, 4), np.int32), np.array([4]), True, "r")
  await node.stop()


class _StubTokenizer:
  """Minimal tokenizer: maps chars to small ids; eos configurable."""

  def __init__(self, eos_token_id: int):
    self.eos_token_id = eos_token_id

  def encode(self, text: str):
    return [(ord(c) % 50) + 1 for c in text][:8]

  def decode(self, ids):
    return " ".join(str(i) for i in ids)


@pytest.mark.asyncio
async def test_node_oneshot_nonstreaming_matches_chunked():
  """A non-streaming request (API hint stream=False) takes the one-dispatch
  fused_generate path and must produce the same tokens as the default
  chunked path."""
  import jax

  from xotorch_support_jetson_tpu.inference.jax_engine import JaxShardedInferenceEngine
  from xotorch_support_jetson_tpu.inference.shard import Shard
  from xotorch_support_jetson_tpu.models.config import tiny_test_config
  from xotorch_support_jetson_tpu.models.decoder import full_model_params

  cfg = tiny_test_config(n_layers=2)
  params, shard = full_model_params(jax.random.PRNGKey(7), cfg, "m")

  async def run(stream_hint):
    engine = JaxShardedInferenceEngine()
    engine.load_test_model(shard, cfg, params, tokenizer=_StubTokenizer(eos_token_id=-1))
    node = Node("n1", StubServer(), engine, NoDiscovery(), None, RingMemoryWeightedPartitioningStrategy(), max_generate_tokens=200, default_sample_temp=0.0)
    await node.start()
    done = asyncio.Event()
    collected = []

    def on_tok(rid, toks, fin):
      collected.extend(toks)
      if fin:
        done.set()

    node.on_token.register("t").on_next(on_tok)
    rid = "req-os"
    node.set_request_options(rid, stream=stream_hint, max_tokens=9, temperature=0.0)
    await node.process_prompt(Shard("m", 0, cfg.n_layers - 1, cfg.n_layers), "hello", rid)
    await asyncio.wait_for(done.wait(), timeout=30)
    await node.stop()
    return collected

  chunked = await run(True)
  oneshot = await run(False)
  assert len(chunked) == 9
  assert oneshot == chunked


@pytest.mark.asyncio
async def test_retry_request_replays_token_history(monkeypatch):
  """Elastic in-flight recovery (reference fails these — SURVEY §5.3):
  a dead next-hop triggers a replay of the full token history as a fresh
  prefill with the restart flag; attempts are bounded."""
  import numpy as np

  from xotorch_support_jetson_tpu.inference.state import InferenceState

  monkeypatch.setenv("XOT_TPU_RETRY_DELAY_S", "0")
  node = make_node()
  await node.start()
  shard = build_base_shard("dummy", "DummyInferenceEngine")

  forwarded = []

  async def fake_forward_tensor(base_shard, tensor, request_id, target_index, inference_state=None):
    forwarded.append((np.asarray(tensor).copy(), inference_state))

  node.forward_tensor = fake_forward_tensor
  state = InferenceState(tokens=np.asarray([[5, 6, 7, 8]], np.int32), prompt_len=2)
  await node._retry_request(shard, "rid-replay", state)

  assert len(forwarded) == 1
  tensor, replay_state = forwarded[0]
  assert tensor.tolist() == [[5, 6, 7, 8]]  # prompt + generated so far
  assert replay_state.extras.get("replay_epoch") == 1
  assert replay_state.prompt_len == 4
  # Successful replay resets the budget: the NEXT failure incident gets the
  # full attempt count again (not a lifetime cap per request).
  assert "rid-replay" not in node._replay_attempts

  # Exhaustion: after the retry budget the request finishes (with an event).
  node._replay_attempts["rid-replay"] = 99
  finished = []
  node.on_token.register("t").on_next(lambda rid, toks, fin: finished.append((rid, fin)))
  await node._retry_request(shard, "rid-replay", state)
  assert ("rid-replay", True) in finished
  await node.stop()


@pytest.mark.asyncio
async def test_engine_restart_flag_resets_session():
  """The replay's restart flag makes the engine prefill from scratch even
  though a session exists for the request id."""
  import jax
  import numpy as np

  from xotorch_support_jetson_tpu.inference.jax_engine import JaxShardedInferenceEngine
  from xotorch_support_jetson_tpu.inference.state import InferenceState
  from xotorch_support_jetson_tpu.models.config import tiny_test_config
  from xotorch_support_jetson_tpu.models.decoder import full_model_params

  cfg = tiny_test_config(n_layers=2)
  params, shard = full_model_params(jax.random.PRNGKey(3), cfg, "m")
  engine = JaxShardedInferenceEngine(use_local_mesh=False)
  engine.load_test_model(shard, cfg, params)

  rid = "replay-me"
  prompt = np.asarray([[4, 9, 2]], np.int32)
  out1, st = await engine.infer_tensor(rid, shard, prompt, None)
  nxt = np.argmax(out1, axis=-1).astype(np.int32).reshape(1, 1)
  out2, st = await engine.infer_tensor(rid, shard, nxt, st)
  assert engine.sessions[rid].curr_pos == 4

  # Replay: full history with a bumped epoch ⇒ session resets, fresh prefill.
  history = np.concatenate([prompt, nxt], axis=1)
  replay = InferenceState(tokens=history.copy(), prompt_len=4, extras={"replay_epoch": 1})
  out3, _ = await engine.infer_tensor(rid, shard, history, replay)
  assert engine.sessions[rid].prompt_len == 4 and engine.sessions[rid].epoch == 1
  # The epoch is read, NOT consumed — it must keep traveling down the ring.
  assert replay.extras.get("replay_epoch") == 1
  np.testing.assert_allclose(out3, out2, rtol=2e-4, atol=2e-4)  # same logits as pre-failure


@pytest.mark.asyncio
async def test_positional_dedup_drops_replayed_span():
  """Token deliveries carry absolute completion positions; a failover that
  regenerates an already-streamed span is dropped by high-water mark — the
  client transcript is the exact concatenation (VERDICT r2 #5)."""
  node = make_node()
  received = []
  node.on_token.register("client").on_next(lambda rid, toks, fin: received.extend(toks))

  rid = "rid-dedup"
  # First attempt streams 3 tokens (remote results over the wire, positioned).
  node.handle_remote_result(rid, [11, 12, 13], False, start_pos=0)
  assert node._emitted_counts[rid] == 3

  # The head dies; a prompt-level retry regenerates from position 0 (greedy
  # => the same prefix), while a zombie broadcast of token 4 races in first.
  node.handle_remote_result(rid, [14], False, start_pos=3)  # late but NEW -> delivered
  node.handle_remote_result(rid, [11, 12], False, start_pos=0)  # replayed, dropped
  node.handle_remote_result(rid, [13, 14], False, start_pos=2)  # replayed, dropped
  node.handle_remote_result(rid, [15], False, start_pos=4)  # regeneration caught up
  node.handle_remote_result(rid, [16], True, start_pos=5)

  assert received == [11, 12, 13, 14, 15, 16]  # exact, no dupes, no gaps
  # The mark survives the finish as a tombstone (expires later) so a
  # straggling zombie broadcast can't reset it and re-deliver the stream.
  assert node._emitted_counts[rid] == 6
  node.handle_remote_result(rid, [11, 12], False, start_pos=0)  # zombie straggler
  assert received == [11, 12, 13, 14, 15, 16]


@pytest.mark.asyncio
async def test_positional_dedup_partial_overlap_and_finish_passthrough():
  """A chunk straddling the high-water mark delivers only its new suffix; a
  fully-replayed chunk produces no event, but finished always gets through."""
  node = make_node()
  events = []
  node.on_token.register("client").on_next(lambda rid, toks, fin: events.append((list(toks), fin)))
  rid = "rid-drop"
  node.trigger_on_token_callbacks(rid, [1, 2], False, start_pos=0)
  node.trigger_on_token_callbacks(rid, [1, 2, 3], False, start_pos=0)  # overlap: only 3 is new
  assert events == [([1, 2], False), ([3], False)]
  node.trigger_on_token_callbacks(rid, [2, 3], False, start_pos=1)  # fully below mark: no event
  assert len(events) == 2
  node.trigger_on_token_callbacks(rid, [3], True, start_pos=2)  # replayed but finished
  assert events[-1] == ([], True)


@pytest.mark.asyncio
async def test_replay_epoch_resets_stale_last_layer_buffer():
  """A surviving last-layer owner adopting a bumped replay_epoch drops its
  stale buffer, so regenerated tokens don't double-count against max_tokens
  (which would truncate the transcript on budget-bound requests)."""
  from xotorch_support_jetson_tpu.inference.state import InferenceState

  node = make_node()
  rid = "rid-epoch"
  shard = Shard("dummy", 0, 7, 8)  # last-layer owner
  node.buffered_token_output[rid] = ([5, 6, 7], False)
  node._completion_offset[rid] = 9

  node._adopt_options(rid, InferenceState(extras={"replay_epoch": 1}), shard)
  assert node.buffered_token_output[rid] == ([], False)
  assert rid not in node._completion_offset
  assert node._seen_epochs[rid] == 1
  # Same epoch again: no further reset (the buffer refills as it regenerates).
  node.buffered_token_output[rid] = ([5], False)
  node._adopt_options(rid, InferenceState(extras={"replay_epoch": 1}), shard)
  assert node.buffered_token_output[rid] == ([5], False)


@pytest.mark.asyncio
async def test_positional_dedup_reorders_ahead_of_mark_chunks():
  """A delivery AHEAD of the contiguous mark (chunks reordered across
  channels mid-failover) is held and released in order once the gap fills —
  no spliced holes, no lost tokens."""
  node = make_node()
  received = []
  node.on_token.register("client").on_next(lambda rid, toks, fin: received.extend(toks))
  rid = "rid-reorder"
  node.handle_remote_result(rid, [1, 2, 3], False, start_pos=0)
  node.handle_remote_result(rid, [6], False, start_pos=5)  # ahead: held
  assert received == [1, 2, 3]
  node.handle_remote_result(rid, [4, 5], False, start_pos=3)  # fills the gap
  assert received == [1, 2, 3, 4, 5, 6]  # held chunk released in order
  assert rid not in node._pending_chunks
  node.handle_remote_result(rid, [7], True, start_pos=6)
  assert received == [1, 2, 3, 4, 5, 6, 7]


@pytest.mark.asyncio
async def test_gap_flush_releases_held_chunks_after_timeout(monkeypatch):
  """A lost broadcast must not stall the stream forever: held ahead-of-mark
  chunks force-flush in order after GAP_FLUSH_S, accepting the hole."""
  import xotorch_support_jetson_tpu.orchestration.node as node_mod

  monkeypatch.setattr(node_mod, "GAP_FLUSH_S", 0.1)
  node = make_node()
  received = []
  node.on_token.register("client").on_next(lambda rid, toks, fin: received.extend(toks))
  rid = "rid-flush"
  node.handle_remote_result(rid, [1, 2], False, start_pos=0)
  node.handle_remote_result(rid, [5, 6], False, start_pos=4)  # positions 2-3 lost
  assert received == [1, 2]
  await asyncio.sleep(0.4)
  assert received == [1, 2, 5, 6]  # flushed past the hole
  node.handle_remote_result(rid, [7], True, start_pos=6)
  assert received == [1, 2, 5, 6, 7]


@pytest.mark.asyncio
async def test_positioned_finish_waits_for_in_flight_tail():
  """A standalone finish delivery that overtakes the final token chunk is
  held until the tail arrives — the stream cannot truncate on RPC reorder."""
  node = make_node()
  events = []
  node.on_token.register("client").on_next(lambda rid, toks, fin: events.append((list(toks), fin)))
  rid = "rid-fin"
  node.handle_remote_result(rid, [1, 2], False, start_pos=0)
  node.handle_remote_result(rid, [], True, start_pos=3)  # finish overtook the tail
  assert events == [([1, 2], False)]  # not finished yet
  node.handle_remote_result(rid, [3], False, start_pos=2)  # tail arrives
  assert events[-1] == ([], True)  # finish released after the tail
  assert [t for toks, _ in events for t in toks] == [1, 2, 3]
