"""Node orchestration tests with the dummy engine (no network, no model)."""

import asyncio

import numpy as np
import pytest

from xotorch_support_jetson_tpu.inference.dummy_engine import DUMMY_EOS, DummyInferenceEngine
from xotorch_support_jetson_tpu.networking.discovery import Discovery
from xotorch_support_jetson_tpu.orchestration.node import Node
from xotorch_support_jetson_tpu.registry import build_base_shard
from xotorch_support_jetson_tpu.topology.partitioning import RingMemoryWeightedPartitioningStrategy


class NoDiscovery(Discovery):
  async def start(self):
    pass

  async def stop(self):
    pass

  async def discover_peers(self, wait_for_peers: int = 0):
    return []


class StubServer:
  async def start(self):
    pass

  async def stop(self):
    pass


def make_node(node_id="n1", max_tokens=200):
  return Node(
    node_id,
    StubServer(),
    DummyInferenceEngine(),
    NoDiscovery(),
    None,
    RingMemoryWeightedPartitioningStrategy(),
    max_generate_tokens=max_tokens,
  )


@pytest.mark.asyncio
async def test_single_node_generates_until_eos():
  node = make_node()
  await node.start()
  shard = build_base_shard("dummy", "DummyInferenceEngine")
  request_id = "req-1"
  callback = node.on_token.register("test")
  done = asyncio.Event()
  collected = []

  def on_tok(rid, tokens, finished):
    if rid == request_id:
      collected.extend(tokens)
      if finished:
        done.set()

  callback.on_next(on_tok)
  # Dummy engine: last-layer output = input + 1, sample takes the last value,
  # so tokens count up deterministically until EOS (69).
  await node.process_prompt(shard, "aaaa", request_id)  # one word, len 4 → token 5
  await asyncio.wait_for(done.wait(), timeout=10)
  assert collected[-1] == DUMMY_EOS
  assert collected == list(range(5, DUMMY_EOS + 1))
  tokens, finished = node.buffered_token_output[request_id]
  assert finished and tokens[-1] == DUMMY_EOS
  await node.stop()


@pytest.mark.asyncio
async def test_single_node_max_tokens_cutoff():
  node = make_node(max_tokens=5)
  await node.start()
  shard = build_base_shard("dummy", "DummyInferenceEngine")
  done = asyncio.Event()
  node.on_token.register("t").on_next(lambda rid, toks, fin: done.set() if fin else None)
  await node.process_prompt(shard, "a", "req-2")
  await asyncio.wait_for(done.wait(), timeout=10)
  tokens, finished = node.buffered_token_output["req-2"]
  assert finished and len(tokens) == 5
  await node.stop()


@pytest.mark.asyncio
async def test_node_status_active_node_tracking():
  node = make_node()
  await node.start()
  assert node.topology.active_node_id in (node.id, None)
  node.on_node_status("r", '{"type": "node_status", "status": "start_process_prompt", "node_id": "other"}')
  assert node.topology.active_node_id == "other"
  node.on_node_status("r", '{"type": "node_status", "status": "end_process_prompt", "node_id": "other"}')
  assert node.topology.active_node_id is None
  await node.stop()


@pytest.mark.asyncio
async def test_single_node_training_step():
  node = make_node()
  await node.start()
  shard = build_base_shard("dummy", "DummyInferenceEngine")
  # Dummy engine has no train(): NotImplementedError per the explicit contract.
  with pytest.raises(NotImplementedError):
    await node.process_example(shard, np.ones((1, 4), np.int32), np.ones((1, 4), np.int32), np.array([4]), True, "r")
  await node.stop()


class _StubTokenizer:
  """Minimal tokenizer: maps chars to small ids; eos configurable."""

  def __init__(self, eos_token_id: int):
    self.eos_token_id = eos_token_id

  def encode(self, text: str):
    return [(ord(c) % 50) + 1 for c in text][:8]

  def decode(self, ids):
    return " ".join(str(i) for i in ids)


@pytest.mark.asyncio
async def test_node_oneshot_nonstreaming_matches_chunked():
  """A non-streaming request (API hint stream=False) takes the one-dispatch
  fused_generate path and must produce the same tokens as the default
  chunked path."""
  import jax

  from xotorch_support_jetson_tpu.inference.jax_engine import JaxShardedInferenceEngine
  from xotorch_support_jetson_tpu.inference.shard import Shard
  from xotorch_support_jetson_tpu.models.config import tiny_test_config
  from xotorch_support_jetson_tpu.models.decoder import full_model_params

  cfg = tiny_test_config(n_layers=2)
  params, shard = full_model_params(jax.random.PRNGKey(7), cfg, "m")

  async def run(stream_hint):
    engine = JaxShardedInferenceEngine()
    engine.load_test_model(shard, cfg, params, tokenizer=_StubTokenizer(eos_token_id=-1))
    node = Node("n1", StubServer(), engine, NoDiscovery(), None, RingMemoryWeightedPartitioningStrategy(), max_generate_tokens=200, default_sample_temp=0.0)
    await node.start()
    done = asyncio.Event()
    collected = []

    def on_tok(rid, toks, fin):
      collected.extend(toks)
      if fin:
        done.set()

    node.on_token.register("t").on_next(on_tok)
    rid = "req-os"
    node.set_request_options(rid, stream=stream_hint, max_tokens=9, temperature=0.0)
    await node.process_prompt(Shard("m", 0, cfg.n_layers - 1, cfg.n_layers), "hello", rid)
    await asyncio.wait_for(done.wait(), timeout=30)
    await node.stop()
    return collected

  chunked = await run(True)
  oneshot = await run(False)
  assert len(chunked) == 9
  assert oneshot == chunked
