"""KV memory hierarchy (inference/kv_tier.py) — ISSUE 6 coverage.

Tentpole: host-RAM page tiering under the paged KV pool. Device-LRU
evictions spill page copies host-side; admission restores host-resident
chain runs into fresh device pages (copy-on-write: the host copies are
retained); release paths donate GENERATED pages under extended chain keys,
so QoS preempt-resume transfers KV instead of recomputing prefill, and
idle multi-turn sessions park their history host-side between turns.

Pinned here: incremental chain-key hashing equals the from-scratch scheme;
PageAllocator invariants under admit/park/preempt/spill/restore churn; the
tier manager's budget/LRU/pending-batch mechanics; ``XOT_TPU_KV_TIER=0``
byte-identity with the single-tier scheduler; preempt-resume token identity
through BOTH the device-cache and forced host-restore paths (lookahead on
and off) against the FIFO solo baseline; > n_slots concurrent multi-turn
sessions on one node with the pool oversubscribed; parked/unparked timeline
stages; restore-failure fallback to recompute; and the cluster prefix
registry round-tripping over a real two-node gRPC cluster.
"""

import asyncio
import hashlib

import jax
import numpy as np
import pytest

from tests.test_batched import _single_row_reference
from xotorch_support_jetson_tpu.inference.batch_scheduler import BatchedServer
from xotorch_support_jetson_tpu.inference.jax_engine import JaxShardedInferenceEngine
from xotorch_support_jetson_tpu.inference.kv_tier import KvTierManager, PrefixRegistry, prefix_registry
from xotorch_support_jetson_tpu.inference.paging import PageAllocator
from xotorch_support_jetson_tpu.inference.qos import QosConfig, QosPolicy
from xotorch_support_jetson_tpu.models.config import tiny_test_config
from xotorch_support_jetson_tpu.models.decoder import full_model_params
from xotorch_support_jetson_tpu.utils.metrics import metrics as gm

CFG = tiny_test_config(n_layers=2, max_seq_len=128)
KEY = jax.random.PRNGKey(0)


def _engine():
  params, shard = full_model_params(KEY, CFG)
  engine = JaxShardedInferenceEngine(use_local_mesh=False)
  engine.load_test_model(shard, CFG, params)
  return engine, params, shard


# ---------------------------------------------------- chain-key hashing


def test_chain_keys_extend_matches_from_scratch_scheme():
  """Satellite: the incremental chain carries the running hash forward.
  Pinned key-equal to the O(pages²) from-scratch scheme (rehash the whole
  chain for every key i), and extension from any prefix equals the full
  build — so a slot extending its prompt keys over generated tokens at
  release produces exactly the keys a fresh admission will compute."""
  ps = 4
  toks = list(range(100, 123))  # 5 full pages + a partial tail

  def from_scratch(tokens, page_size):
    # The reference scheme: key i walks pages 0..i every time.
    arr = np.asarray(tokens, dtype=np.int64)
    keys = []
    for i in range(len(arr) // page_size):
      prev = b""
      for j in range(i + 1):
        prev = hashlib.blake2b(prev + arr[j * page_size : (j + 1) * page_size].tobytes(), digest_size=16).digest()
      keys.append(prev)
    return keys

  full = PageAllocator.chain_keys(toks, ps)
  assert full == from_scratch(toks, ps)
  assert len(full) == len(toks) // ps
  for cut in range(len(full) + 1):
    assert PageAllocator.chain_keys_extend(full[:cut], toks, ps) == full
  # Extending over a longer absorbed sequence only hashes the NEW pages and
  # keeps the shared prefix keys identical (the donation/admission join).
  longer = toks + list(range(7))
  ext = PageAllocator.chain_keys_extend(full, longer, ps)
  assert ext[: len(full)] == full
  assert ext == PageAllocator.chain_keys(longer, ps)
  # Same ids in any integer dtype hash identically (normalized to int64).
  assert PageAllocator.chain_keys(np.asarray(toks, np.int32), ps) == full


# ------------------------------------------------- allocator invariants


def test_allocator_invariants_under_churn():
  """Satellite: property-style churn over admit/release/donate/evict-spill/
  restore-adopt sequences. After every operation: no page double-freed,
  leaked, or in two states at once — free + cached + in-use always equals
  the pool size — and the spill hook saw every evicted cached page exactly
  once BEFORE it was reused."""
  rng = np.random.default_rng(7)
  ps = 4
  alloc = PageAllocator(33, ps)  # 32 usable pages
  spilled: list[tuple[bytes, int]] = []
  alloc.spill_hook = lambda batch: spilled.extend(batch)

  in_use: list[list[int]] = []  # private page sets held by fake requests
  held_refs: list[list[int]] = []  # shared (refcounted) pages held
  key_seq = 0

  def check():
    state = alloc.audit()
    private = sum(len(p) for p in in_use)
    assert state["free"] + state["cached"] + private == alloc.n_pages - 1
    assert state["referenced"] <= state["cached"]
    # Every key in this test is inserted under the cache exactly once, so
    # the spill hook must deliver each (key, page) pair at most once across
    # the whole run — a duplicate means a double-eviction/double-spill.
    assert len(spilled) == len(set(spilled))

  for step in range(600):
    op = rng.integers(0, 5)
    if op == 0:  # admit: alloc private pages (may evict-spill)
      n = int(rng.integers(1, 5))
      got = alloc.alloc(n)
      if got is not None:
        assert len(set(got)) == n
        in_use.append(got)
        held_refs.append([])
    elif op == 1 and in_use:  # release: donate some pages, free the rest
      i = int(rng.integers(0, len(in_use)))
      pages, refs = in_use.pop(i), held_refs.pop(i)
      for p in refs:
        alloc.release(p)
      to_free = []
      for p in pages:
        key_seq += 1
        if rng.random() < 0.5 and alloc.insert_cached(f"k{key_seq}".encode(), p):
          continue
        to_free.append(p)
      alloc.free(to_free)
    elif op == 2:  # prefix lookup: take refs on cached pages
      keys = [k for k, _ in spilled[-3:]] if rng.random() < 0.3 else []
      got = alloc.lookup_prefix([k for k in keys if k in alloc._by_key][:2])
      if in_use:
        held_refs[int(rng.integers(0, len(held_refs)))].extend(got)
      else:
        for p in got:
          alloc.release(p)
    elif op == 3:  # restore-adopt: a host hit becomes a cached+referenced page
      got = alloc.alloc(1)
      if got is not None:
        key_seq += 1
        alloc.adopt_restored(f"r{key_seq}".encode(), got[0])
        if in_use:
          held_refs[int(rng.integers(0, len(held_refs)))].append(got[0])
        else:
          alloc.release(got[0])
    elif op == 4 and in_use:  # preempt: release refs, free all private pages
      i = int(rng.integers(0, len(in_use)))
      pages, refs = in_use.pop(i), held_refs.pop(i)
      for p in refs:
        alloc.release(p)
      alloc.free(pages)
    check()

  # Drain everything: the pool must account exactly, nothing leaked.
  while in_use:
    pages, refs = in_use.pop(), held_refs.pop()
    for p in refs:
      alloc.release(p)
    alloc.free(pages)
  state = alloc.audit()
  assert state["free"] + state["cached"] == alloc.n_pages - 1
  assert state["referenced"] == 0
  # Every spill batch was delivered before its pages could be reused; keys
  # seen by the hook were cache keys at eviction time.
  assert all(isinstance(k, bytes) and isinstance(p, int) for k, p in spilled)


# ------------------------------------------------- tier manager mechanics


class _FakePool:
  """Numpy-backed stand-in for the device pool: read/write callbacks with
  the real contract, no jax involved."""

  def __init__(self, n_pages: int, leafs=("k", "v")):
    self.data = {name: rnginit(i, n_pages) for i, name in enumerate(leafs)}

  def read(self, pages):
    return {name: arr[:, pages] for name, arr in self.data.items()}, len(pages)

  def write(self, pages, data):
    for name, arr in self.data.items():
      arr[:, pages] = data[name]


def rnginit(seed, n_pages):
  return np.random.default_rng(seed).standard_normal((2, n_pages, 3, 4, 5)).astype(np.float32)


def test_tier_manager_spill_restore_cow_and_budget():
  pool = _FakePool(16)
  writes: list[tuple] = []

  def write(pages, data):
    writes.append((list(pages), data))
    pool.write(pages, data)

  page_bytes = sum(int(np.prod(a.shape[2:])) * a.shape[0] * a.dtype.itemsize for a in pool.data.values())
  tier = KvTierManager(page_size=4, read_pages=pool.read, write_pages=write,
                       budget_bytes=page_bytes * 3, max_inflight=1)
  keys = [f"key{i}".encode() for i in range(5)]
  golden = {k: {n: pool.data[n][:, i + 1].copy() for n in pool.data} for i, k in enumerate(keys)}

  tier.spill([(keys[0], 1), (keys[1], 2)])
  tier.spill([(keys[2], 3)])
  assert tier.host_pages == 3 and tier.host_bytes == page_bytes * 3
  assert tier.host_run(keys, 0, 5) == keys[:3]
  assert tier.host_run(keys, 1, 2) == [keys[1]]
  assert gm.gauges["kv_tier_host_pages"] == 3

  # Restore into fresh pages; COW — the host entries are retained.
  pool.data = {n: np.zeros_like(a) for n, a in pool.data.items()}  # "evicted" device side
  tier.restore_into(keys[:2], [7, 8], request_id="r-restore")
  assert writes and writes[-1][0] == [7, 8]
  for i, k in enumerate(keys[:2]):
    for n in golden[k]:
      np.testing.assert_array_equal(pool.data[n][:, 7 + i], golden[k][n])
  assert tier.host_has(keys[0]) and tier.host_pages == 3  # retained (COW)

  # Budget: a 4th page evicts the host-LRU oldest (keys[2] was least
  # recently touched — the restore LRU-bumped keys[0..1]).
  tier.spill([(keys[3], 4)])
  assert tier.host_pages == 3 and not tier.host_has(keys[2])
  assert tier.host_has(keys[0]) and tier.host_has(keys[3])

  # A restore of an evicted key raises; the scheduler treats that as "fall
  # back to recompute".
  with pytest.raises(KeyError):
    tier.restore_into([keys[2]], [9])

  # Timeline stage landed on the restoring request.
  from xotorch_support_jetson_tpu.orchestration.tracing import tracer

  tl = tracer.timeline("r-restore")
  assert tl is not None and any(e["stage"] == "restored" for e in tl["events"])

  tier.clear()
  assert tier.host_pages == 0 and tier.host_bytes == 0


def test_tier_manager_fifo_policy_and_pending_inflight():
  """``XOT_TPU_KV_TIER_EVICT=fifo`` skips the LRU touch on restore;
  ``max_inflight`` bounds pending async batches (older ones materialize)."""
  pool = _FakePool(16)
  page_bytes = sum(int(np.prod(a.shape[2:])) * a.shape[0] * a.dtype.itemsize for a in pool.data.values())
  tier = KvTierManager(page_size=4, read_pages=pool.read, write_pages=pool.write,
                       budget_bytes=page_bytes * 2, evict_policy="fifo", max_inflight=2)
  keys = [f"f{i}".encode() for i in range(3)]
  tier.spill([(keys[0], 1)])
  tier.restore_into([keys[0]], [5])  # would LRU-bump under "lru"
  tier.spill([(keys[1], 2)])
  tier.spill([(keys[2], 3)])  # budget 2: evicts the FIFO-oldest = keys[0]
  assert not tier.host_has(keys[0]) and tier.host_has(keys[1]) and tier.host_has(keys[2])
  assert len(tier._pending) <= 2


# --------------------------------------------- scheduler-level behaviors


def _run(coro):
  return asyncio.run(coro)


def test_kv_tier_off_is_single_tier_pinned(monkeypatch):
  """XOT_TPU_KV_TIER=0: no tier manager, no spill hook, donation limited to
  PROMPT pages (generated pages free immediately) — and the stream is
  byte-identical to the tier-on run (greedy decode: the tier only changes
  where KV bytes live, never their values)."""
  prompt, n = [3, 25, 9, 14, 7, 2, 81, 5, 6], 8

  def serve(tier_on: bool):
    if tier_on:
      monkeypatch.delenv("XOT_TPU_KV_TIER", raising=False)
    else:
      monkeypatch.setenv("XOT_TPU_KV_TIER", "0")
    monkeypatch.setenv("XOT_TPU_PAGE_SIZE", "4")
    engine, _, _ = _engine()
    server = BatchedServer(engine, n_slots=2, chunk=2, qos=False)
    out = _run(server.submit("t", np.asarray(prompt, np.int32), max_tokens=n, temp=0.0, top_k=35, eos_ids=(), emit=lambda *_: None))
    state = server.allocator.audit()
    tier = server.tier
    hook = server.allocator.spill_hook
    server.shutdown()
    return out, state, tier, hook

  out_off, state_off, tier_off, hook_off = serve(False)
  assert tier_off is None and hook_off is None
  # Single-tier donation: exactly the prompt's full pages stay cached.
  assert state_off["cached"] == len(prompt) // 4
  out_on, state_on, tier_on, hook_on = serve(True)
  assert tier_on is not None and hook_on is not None
  assert out_on == out_off
  # Tiered donation covers the generated full pages too: (S + n - 1) // ps.
  assert state_on["cached"] == (len(prompt) + n - 1) // 4


@pytest.mark.parametrize("lookahead", [True, False])
def test_preempt_resume_restore_token_identity(lookahead, monkeypatch):
  """Acceptance: a preempted-then-resumed greedy stream with tiering ON
  resumes by TRANSFER (its absorbed prompt hits the donated pages as a
  prefix) and stays byte-identical to the FIFO solo baseline — which
  test_qos.py separately pins equal to the recompute path — lookahead on
  and off. The admission's reuse is asserted, not assumed."""
  monkeypatch.delenv("XOT_TPU_KV_TIER", raising=False)
  monkeypatch.setenv("XOT_TPU_PAGE_SIZE", "4")  # full pages exist at these lengths
  engine, params, shard = _engine()
  server = BatchedServer(engine, n_slots=1, chunk=2, lookahead=lookahead, qos=QosPolicy(QosConfig(aging_s=10_000.0)))
  p_batch, p_int = [3, 25, 9], [7, 1, 88, 42, 5]
  n_batch, n_int = 24, 4
  solo_batch = _single_row_reference(params, shard, p_batch, n_batch - 1)
  solo_int = _single_row_reference(params, shard, p_int, n_int - 1)
  before_pre = gm.counter_value("qos_preemptions_total")
  before_hits = gm.counter_value("prefix_cache_hit_pages_total")
  streams: dict[str, list] = {}

  async def run():
    started = asyncio.Event()

    def emit(rid, toks, fin):
      streams.setdefault(rid, []).extend(toks)
      if rid == "bg" and len(streams["bg"]) >= 4:
        started.set()

    bg = asyncio.create_task(server.submit("bg", np.asarray(p_batch, np.int32), max_tokens=n_batch, temp=0.0, top_k=35, eos_ids=(), emit=emit, priority="batch"))
    await asyncio.wait_for(started.wait(), timeout=30)
    out_int = await asyncio.wait_for(
      server.submit("vip", np.asarray(p_int, np.int32), max_tokens=n_int, temp=0.0, top_k=35, eos_ids=(), emit=emit, priority="interactive"),
      timeout=60,
    )
    return out_int, await asyncio.wait_for(bg, timeout=60)

  out_int, out_bg = _run(run())
  assert gm.counter_value("qos_preemptions_total") > before_pre
  assert out_bg == solo_batch and streams["bg"] == solo_batch
  assert out_int == solo_int
  # The resume really reused donated pages (transfer, not recompute): the
  # prefix-hit counter moved — the 3-token prompt alone can't fill a page,
  # so the hits are the preempt donation's extended (generated-token) pages
  # found device-cached at resume.
  assert gm.counter_value("prefix_cache_hit_pages_total") > before_hits
  assert all(s is None for s in server.slots)
  server.allocator.audit()
  server.shutdown()


@pytest.mark.parametrize("lookahead,kv_quant", [(True, ""), (False, ""), (True, "int4")])
def test_preempt_resume_via_host_restore_identity(lookahead, kv_quant, monkeypatch):
  """Acceptance (host path): the pool is sized so the preempting request's
  own footprint EVICTS the victim's donated pages — they spill host-side,
  and the resume restores them from the HOST tier. Stream identity against
  the FIFO solo baseline still holds, and the restore counters prove the
  path taken. The ``int4`` point (ISSUE 11) drives the same
  spill→evict→restore cycle over PACKED pages: the tier moves half the
  bytes per page and the restored stream stays byte-identical to the
  never-spilled int4 run (the solo baseline runs the same quant mode)."""
  monkeypatch.delenv("XOT_TPU_KV_TIER", raising=False)
  if kv_quant:
    monkeypatch.setenv("XOT_TPU_KV_QUANT", kv_quant)
  monkeypatch.setenv("XOT_TPU_PAGE_SIZE", "4")
  monkeypatch.setenv("XOT_TPU_BATCH_PAGES", "6")  # 5 usable: vip's footprint alone
  engine, params, shard = _engine()
  server = BatchedServer(engine, n_slots=1, chunk=2, lookahead=lookahead, qos=QosPolicy(QosConfig(aging_s=10_000.0)))
  p_batch = [3, 25, 9]
  p_int = [7, 1, 88, 42, 5, 11, 23, 4, 91, 33, 8, 17, 2]  # 13 tokens: 4 pages at admission, 5 by finish
  n_batch, n_int = 10, 4
  solo_batch = _single_row_reference(params, shard, p_batch, n_batch - 1)
  solo_int = _single_row_reference(params, shard, p_int, n_int - 1)
  before_pre = gm.counter_value("qos_preemptions_total")
  before_spill = gm.counter_value("kv_tier_spilled_pages_total")
  before_restore = gm.counter_value("kv_tier_restored_pages_total")
  streams: dict[str, list] = {}

  async def run():
    started = asyncio.Event()

    def emit(rid, toks, fin):
      streams.setdefault(rid, []).extend(toks)
      if rid == "bg" and len(streams["bg"]) >= 4:
        started.set()

    bg = asyncio.create_task(server.submit("bg", np.asarray(p_batch, np.int32), max_tokens=n_batch, temp=0.0, top_k=35, eos_ids=(), emit=emit, priority="batch"))
    await asyncio.wait_for(started.wait(), timeout=30)
    out_int = await asyncio.wait_for(
      server.submit("vip", np.asarray(p_int, np.int32), max_tokens=n_int, temp=0.0, top_k=35, eos_ids=(), emit=emit, priority="interactive"),
      timeout=60,
    )
    return out_int, await asyncio.wait_for(bg, timeout=60)

  out_int, out_bg = _run(run())
  assert gm.counter_value("qos_preemptions_total") > before_pre
  assert out_int == solo_int
  assert out_bg == solo_batch and streams["bg"] == solo_batch
  # The victim's donated pages were spilled by the vip's allocations and the
  # resume restored at least one of them from HOST RAM.
  assert gm.counter_value("kv_tier_spilled_pages_total") > before_spill
  assert gm.counter_value("kv_tier_restored_pages_total") > before_restore
  # Timeline surfacing: the resume carries a ``restored`` stage.
  from xotorch_support_jetson_tpu.orchestration.tracing import tracer

  tl = tracer.timeline("bg")
  assert tl is not None and any(e["stage"] == "restored" for e in tl["events"])
  assert all(s is None for s in server.slots)
  server.allocator.audit()
  server.shutdown()


def test_open_sessions_exceed_slots_with_host_parking(monkeypatch):
  """Acceptance: one node holds MORE concurrent multi-turn sessions than
  n_slots by parking idle sessions' pages (device cache → host tier under
  pressure) and restoring on the next turn. Every turn of every session is
  token-identical to its solo greedy reference, the allocator invariant
  stays green throughout, and the tier actually spilled and restored."""
  monkeypatch.delenv("XOT_TPU_KV_TIER", raising=False)
  monkeypatch.setenv("XOT_TPU_PAGE_SIZE", "4")
  monkeypatch.setenv("XOT_TPU_BATCH_PAGES", "13")  # 12 usable: ~2.5x oversubscribed
  engine, params, shard = _engine()
  n_slots, n_sessions, n_turns, per_turn = 2, 6, 3, 4
  server = BatchedServer(engine, n_slots=n_slots, chunk=2, qos=False)
  before_spill = gm.counter_value("kv_tier_spilled_pages_total")
  before_restore = gm.counter_value("kv_tier_restored_pages_total")
  peak_open = 0

  async def session(s: int, results: list):
    prompt = [10 + s, 40 + s, 70 + s]
    for turn in range(n_turns):
      rid = f"sess{s}-t{turn}"
      out = await asyncio.wait_for(
        server.submit(rid, np.asarray(prompt, np.int32), max_tokens=per_turn, temp=0.0, top_k=35, eos_ids=(), emit=lambda *_: None),
        timeout=120,
      )
      results.append((s, turn, list(prompt), out))
      prompt = prompt + out + [5 + s + turn]  # next user turn
      await asyncio.sleep(0.001 * s)  # idle between turns: pages park

  async def run():
    nonlocal peak_open
    results: list = []
    tasks = [asyncio.create_task(session(s, results)) for s in range(n_sessions)]
    while any(not t.done() for t in tasks):
      open_now = len({r.get_name() for r in tasks if not r.done()})
      peak_open = max(peak_open, open_now)
      if server.allocator is not None:  # created with the pool on first admit
        server.allocator.audit()  # invariant green THROUGHOUT
      await asyncio.sleep(0.01)
    await asyncio.gather(*tasks)
    return results

  results = _run(run())
  assert len(results) == n_sessions * n_turns
  assert peak_open > n_slots  # more live sessions than slots, concurrently
  for s, turn, prompt, out in results:
    assert out == _single_row_reference(params, shard, prompt, per_turn - 1), (s, turn)
  # The pool (12 pages) cannot hold 6 sessions' history (~5 pages each by
  # turn 3): parking spilled host-side and later turns restored.
  assert gm.counter_value("kv_tier_spilled_pages_total") > before_spill
  assert gm.counter_value("kv_tier_restored_pages_total") > before_restore
  server.allocator.audit()
  server.shutdown()


def test_parked_unparked_timeline_stages(monkeypatch):
  """Satellite: a page-starved request's timeline carries ``parked`` and a
  matching ``unparked`` with the measured wait, and the timeline's
  top-level ``parked_ms`` explains the starvation span."""
  monkeypatch.delenv("XOT_TPU_KV_TIER", raising=False)
  monkeypatch.setenv("XOT_TPU_PAGE_SIZE", "4")
  monkeypatch.setenv("XOT_TPU_BATCH_PAGES", "6")  # 5 usable
  engine, _, _ = _engine()
  server = BatchedServer(engine, n_slots=2, chunk=2, qos=False)

  async def run():
    # hog: 13-token prompt -> 4 pages at admission, 5 in flight; starver
    # can't get its 2 pages until hog finishes.
    hog = asyncio.create_task(server.submit("hog", np.asarray(list(range(30, 43)), np.int32), max_tokens=4, temp=0.0, top_k=35, eos_ids=(), emit=lambda *_: None))
    await asyncio.sleep(0)
    starver = asyncio.create_task(server.submit("starver", np.asarray([3, 25, 9, 14, 7], np.int32), max_tokens=3, temp=0.0, top_k=35, eos_ids=(), emit=lambda *_: None))
    await asyncio.wait_for(asyncio.gather(hog, starver), timeout=60)

  _run(run())
  from xotorch_support_jetson_tpu.orchestration.tracing import tracer

  tl = tracer.timeline("starver")
  assert tl is not None
  stages = [e["stage"] for e in tl["events"]]
  assert "parked" in stages and "unparked" in stages
  assert stages.index("unparked") > stages.index("parked")
  un = next(e for e in tl["events"] if e["stage"] == "unparked")
  assert un["attributes"]["waited_ms"] > 0
  assert tl["parked_ms"] > 0
  server.shutdown()


def test_restore_run_stops_at_device_cached_suffix(monkeypatch):
  """Regression: pages evict in chain order, so a chain's SUFFIX can outlive
  its evicted prefix in the device LRU while the whole chain is host-resident.
  The restore run must stop at the first key still device-cached (adopting a
  cached key would corrupt the key<->page maps); the admission still succeeds,
  restores the evicted prefix from host, and streams token-identically."""
  monkeypatch.delenv("XOT_TPU_KV_TIER", raising=False)
  monkeypatch.setenv("XOT_TPU_PAGE_SIZE", "4")
  engine, params, shard = _engine()
  server = BatchedServer(engine, n_slots=1, chunk=2, qos=False)
  prompt = [3, 25, 9, 14, 7, 2, 81, 5, 6, 44, 12, 31, 19]  # 13 tokens: 3 full pages
  solo = _single_row_reference(params, shard, prompt, 3)

  async def run():
    out1 = await server.submit("t1", np.asarray(prompt, np.int32), max_tokens=4, temp=0.0, top_k=35, eos_ids=(), emit=lambda *_: None)
    assert out1 == solo
    # Spill EVERY donated page host-side, then re-admit: the whole chain
    # restores and is device-cached again (host copies retained, COW).
    server.allocator._evict(len(server.allocator._lru))
    out2 = await server.submit("t2", np.asarray(prompt, np.int32), max_tokens=4, temp=0.0, top_k=35, eos_ids=(), emit=lambda *_: None)
    assert out2 == solo
    # Evict only the LRU-oldest donated page — the chain's FIRST key — so
    # the device holds the suffix while the host holds the whole chain.
    server.allocator._evict(1)
    before = gm.counter_value("kv_tier_restored_pages_total")
    out3 = await server.submit("t3", np.asarray(prompt, np.int32), max_tokens=4, temp=0.0, top_k=35, eos_ids=(), emit=lambda *_: None)
    assert out3 == solo  # would raise AssertionError without the run trim
    assert gm.counter_value("kv_tier_restored_pages_total") > before

  _run(run())
  server.allocator.audit()
  server.shutdown()


def test_restore_failure_falls_back_to_recompute(monkeypatch):
  """A failed device write on the restore path must cost only the missed
  optimization: the pages stay private, prefill recomputes, and the stream
  is still correct (carry/recompute is the pinned correctness fallback)."""
  monkeypatch.delenv("XOT_TPU_KV_TIER", raising=False)
  monkeypatch.setenv("XOT_TPU_PAGE_SIZE", "4")
  monkeypatch.setenv("XOT_TPU_BATCH_PAGES", "6")
  engine, params, shard = _engine()
  server = BatchedServer(engine, n_slots=1, chunk=2, qos=False)
  prompt = [3, 25, 9, 14, 7, 2, 81, 5]
  solo = _single_row_reference(params, shard, prompt, 3)

  async def run():
    # Turn 1 caches the prompt pages; the follow-up turn would restore any
    # host-spilled ones. Break the write path first.
    out1 = await server.submit("a", np.asarray(prompt, np.int32), max_tokens=4, temp=0.0, top_k=35, eos_ids=(), emit=lambda *_: None)
    assert out1 == solo
    # Force every cached page host-side, then break restores.
    server.allocator._evict(len(server.allocator._lru))

    def broken_write(pages, data):
      raise RuntimeError("injected restore failure")

    monkeypatch.setattr(server.tier, "_write", broken_write)
    p2 = prompt + out1 + [50]
    out2 = await server.submit("b", np.asarray(p2, np.int32), max_tokens=4, temp=0.0, top_k=35, eos_ids=(), emit=lambda *_: None)
    assert out2 == _single_row_reference(params, shard, p2, 3)

  _run(run())
  server.allocator.audit()
  server.shutdown()


# --------------------------------------------------- cluster prefix registry


def test_prefix_registry_bounds_and_hints():
  reg = PrefixRegistry(max_keys=4)
  keys = [f"k{i}".encode() for i in range(6)]
  reg.note(keys)
  assert len(reg.local_hexes()) == 4  # bounded, most recent kept
  assert reg.local_hexes()[0] == keys[-1].hex()  # most-recent-first
  reg.update_remote("peer-a", [keys[0].hex(), "zz-not-hex", keys[1].hex()])
  assert reg.locate(keys[0]) == ["peer-a"]
  assert reg.locate(keys[5]) == []
  # An advert REPLACES the previous one (snapshot semantics).
  reg.update_remote("peer-a", [keys[2].hex()])
  assert reg.locate(keys[0]) == [] and reg.locate(keys[2]) == ["peer-a"]
  reg.forget_remote("peer-a")
  assert reg.locate(keys[2]) == []
  snap = reg.snapshot()
  assert snap["local_keys"] == 4 and snap["remote"] == {}
  reg.clear_local()
  assert reg.local_hexes() == []


@pytest.mark.asyncio
async def test_prefix_registry_roundtrip_over_grpc_cluster():
  """Acceptance: the cluster prefix registry round-trips over the REAL
  two-node gRPC cluster — node1's advertised chain keys become visible to
  node0's registry via prefix_pull/prefix_keys on the opaque-status
  channel, and locate() resolves them to node1."""
  from tests.test_networking import _make_cluster

  nodes = await _make_cluster(2)
  keys = [hashlib.blake2b(f"prefix-{i}".encode(), digest_size=16).digest() for i in range(3)]
  try:
    prefix_registry.clear()
    prefix_registry.note(keys)  # both nodes share the process-global registry:
    # node1's reply advertises these as ITS local keys, and node0's update
    # lands them under remote["node1"] — the full wire round trip.
    counts = await nodes[0].collect_cluster_prefixes(timeout=5.0)
    assert counts.get("node1", 0) >= 3
    for k in keys:
      assert "node1" in prefix_registry.locate(k)
    snap = prefix_registry.snapshot()
    assert snap["remote"]["node1"] >= 3
  finally:
    prefix_registry.clear()
    for node in nodes:
      await node.stop()


@pytest.mark.asyncio
async def test_kv_tier_api_endpoint():
  """GET /v1/kv/tier surfaces the hierarchy: enabled flag, host occupancy,
  spill/restore totals, and the prefix registry view."""
  from aiohttp.test_utils import TestClient, TestServer

  from tests_support_stubs import NoDiscovery, StubServer
  from xotorch_support_jetson_tpu.api.chatgpt_api import ChatGPTAPI
  from xotorch_support_jetson_tpu.inference.dummy_engine import DummyInferenceEngine
  from xotorch_support_jetson_tpu.orchestration.node import Node
  from xotorch_support_jetson_tpu.topology.partitioning import RingMemoryWeightedPartitioningStrategy

  node = Node("kvtier-api-node", StubServer(), DummyInferenceEngine(), NoDiscovery(), None, RingMemoryWeightedPartitioningStrategy())
  await node.start()
  api = ChatGPTAPI(node, "DummyInferenceEngine", default_model="dummy")
  client = TestClient(TestServer(api.app))
  await client.start_server()
  try:
    resp = await client.get("/v1/kv/tier")
    assert resp.status == 200
    body = await resp.json()
    assert set(body) >= {"enabled", "host", "spilled_pages_total", "restored_pages_total", "prefix_registry"}
    assert isinstance(body["prefix_registry"]["local_keys"], int)
    # scope=cluster with no peers degrades gracefully.
    resp = await client.get("/v1/kv/tier?scope=cluster")
    assert resp.status == 200
  finally:
    await client.close()
    await node.stop()


# ------------------------------------- quant-mode round trips (ISSUE 11)


@pytest.mark.parametrize("quant", ["int8", "int4"])
def test_tier_round_trip_byte_identical_both_quant_modes(quant):
  """Spill → device eviction (pages zeroed) → host restore → wire adopt on a
  SECOND tier, over a REAL jax page pool in both quant modes: every
  restored leaf is byte-identical to the never-spilled pages, the int4
  pool's code leaves are packed (half the bytes), and the adopt guard
  refuses a mismatched quant tag before the byte-geometry guard can be
  seeded with a foreign layout."""
  import jax.numpy as jnp

  from xotorch_support_jetson_tpu.inference.kv_tier import gather_pages, scatter_pages
  from xotorch_support_jetson_tpu.networking.grpc.serialization import (
    kv_pages_to_proto,
    proto_to_kv_pages,
    quant_from_wire,
  )
  from xotorch_support_jetson_tpu.ops.paged import init_paged_pool

  rng = np.random.default_rng(61)
  ps, P = 8, 9
  box = {"pool": init_paged_pool(CFG, 2, P, ps, quant=quant)}
  assert box["pool"]["k"].dtype == jnp.int8
  kd = CFG.cache_k_dim // (2 if quant == "int4" else 1)
  assert box["pool"]["k"].shape[-1] == kd
  # Fill the real pool with arbitrary code/scale bytes.
  filled = {}
  for name, leaf in box["pool"].items():
    if name.endswith("_scale"):
      filled[name] = jnp.asarray(rng.uniform(0.005, 0.05, size=leaf.shape), jnp.float32)
    else:
      filled[name] = jnp.asarray(rng.integers(-128, 128, size=leaf.shape), jnp.int8)
  box["pool"] = filled
  golden = {name: np.asarray(leaf)[:, [2, 3, 5]].copy() for name, leaf in box["pool"].items()}

  def read(pages):
    return {name: leaf[:, np.asarray(pages)] for name, leaf in box["pool"].items()}, len(pages)

  def write(pages, data):
    box["pool"] = scatter_pages(box["pool"], pages, data)

  tier = KvTierManager(page_size=ps, read_pages=read, write_pages=write, budget_bytes=1 << 24)
  tier.kv_quant = quant
  keys = [f"rt-{quant}-{i}".encode() for i in range(3)]
  tier.spill(list(zip(keys, [2, 3, 5])))
  # Device "reuses" the evicted pages: zero them out.
  box["pool"] = {name: leaf.at[:, [2, 3, 5]].set(0) for name, leaf in box["pool"].items()}
  # Restore into fresh pages — byte-identical to the never-spilled copies.
  tier.restore_into(keys, [6, 7, 8])
  for name in golden:
    np.testing.assert_array_equal(np.asarray(box["pool"][name])[:, [6, 7, 8]], golden[name], err_msg=f"{quant}/{name}")

  # Wire adopt on a second tier: serialize -> parse -> adopt -> restore.
  dev, n = read([6, 7, 8])
  leaves = {name: np.asarray(arr)[:, :n] for name, arr in dev.items()}
  msg = kv_pages_to_proto("rt", keys, leaves, page_size=ps, seq=0, last=True, quant=quant)
  keys2, leaves2 = proto_to_kv_pages(msg)
  assert keys2 == keys
  box2 = {"pool": {name: jnp.zeros_like(leaf) for name, leaf in box["pool"].items()}}

  def write2(pages, data):
    box2["pool"] = scatter_pages(box2["pool"], pages, data)

  tier2 = KvTierManager(page_size=ps, read_pages=read, write_pages=write2, budget_bytes=1 << 24)
  tier2.kv_quant = quant
  # Mismatched tag refused up front (int8<->int4 cross); untagged accepted.
  other = "int8" if quant == "int4" else "int4"
  assert tier2.adopt_wire(keys2, leaves2, quant=other) == 0
  assert tier2.adopt_wire(keys2, leaves2, quant=quant_from_wire(msg.quant)) == 3
  tier2.restore_into(keys2, [1, 2, 3])
  for name in golden:
    np.testing.assert_array_equal(np.asarray(box2["pool"][name])[:, [1, 2, 3]], golden[name], err_msg=f"wire {quant}/{name}")


def test_kv_page_wire_payload_halves_under_int4():
  """Pinned via proto payload accounting (ISSUE 11 criterion): the SAME
  pages under int4 ship ~half the int8 payload bytes (codes halve; the f32
  scales are unchanged, so the exact ratio is (hd/2 + 4)/(hd + 4))."""
  from xotorch_support_jetson_tpu.networking.grpc.serialization import kv_pages_to_proto, proto_payload_bytes
  from xotorch_support_jetson_tpu.ops.paged import init_paged_pool

  cfg = tiny_test_config(dim=512, n_heads=8, n_kv_heads=8)  # hd=64, the production geometry
  ps, P, n = 16, 5, 3
  keys = [f"pb{i}".encode() for i in range(n)]
  sizes = {}
  for quant in ("int8", "int4"):
    pool = init_paged_pool(cfg, 2, P, ps, quant=quant)
    leaves = {name: np.asarray(leaf[:, 1 : 1 + n]) for name, leaf in pool.items()}
    msg = kv_pages_to_proto("pb", keys, leaves, page_size=ps, seq=0, last=True, quant=quant)
    assert msg.quant == quant
    sizes[quant] = proto_payload_bytes(msg)
  hd = cfg.head_dim
  expect = (hd / 2 + 4) / (hd + 4)  # 0.53 at hd=64
  assert sizes["int4"] < 0.60 * sizes["int8"]
  assert abs(sizes["int4"] / sizes["int8"] - expect) < 0.05
