"""int4-KV pages + the retuned paged dispatch (ISSUE 11).

Correctness claims:
- int4 KV pack/unpack round-trips exactly and the dequant error is bounded;
- the Pallas paged kernel's in-kernel int4 dequant (two-dot nibble split)
  == the gather reference consuming the SAME packed pools + scales —
  token-exact at the same quantization, across page-tile widths;
- the new wide page tiles (8/16 — the shape-aware retune) stay exact for
  int8 pools too;
- paged int4-KV decode == dense int4-KV decode, token for token (int4 is
  exact vs its OWN quantized reference — never vs int8/bf16);
- the decision matrix: quantized pages dispatch the kernel at every batched
  shape (B in {16, 48, 96} × {int8, int4}), and ``resolved_decode_path``
  attribution can never disagree with ``select_decode_path`` across the
  full (batch, context, quant, tile) grid;
- scheduler pool block math under int4: ~2x the int8 pages at the same
  bf16 dense budget, enough that the dense-48 budget covers 96 FULL context
  windows (the B>=96 admission knee) — and requests still serve.
"""

import asyncio

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from xotorch_support_jetson_tpu.inference.jax_engine import JaxShardedInferenceEngine
from xotorch_support_jetson_tpu.models.config import tiny_test_config
from xotorch_support_jetson_tpu.models.decoder import (
  full_model_params,
  fused_batch_decode,
  fused_paged_batch_decode,
  init_kv_cache,
  prefill_into_pages_many,
  prefill_into_slots,
)
from xotorch_support_jetson_tpu.models.quantize import quantize_kv_int4, unpack_int4_kv
from xotorch_support_jetson_tpu.ops.paged import (
  init_paged_pool,
  paged_decode_attention,
  paged_gqa_attention_ref,
)

CFG = tiny_test_config(n_layers=2, max_seq_len=128)
KEY = jax.random.PRNGKey(0)
PS = 16


def test_quantize_kv_int4_roundtrip_and_bounds():
  rng = np.random.default_rng(3)
  x = jnp.asarray(rng.normal(size=(5, 3, 64)), jnp.float32)
  packed, scale = quantize_kv_int4(x)
  assert packed.shape == (5, 3, 32) and packed.dtype == jnp.int8
  assert scale.shape == (5, 3, 1)
  codes = unpack_int4_kv(packed)
  assert codes.shape == x.shape
  # Nibble range and pack/unpack exactness (unpack(pack(q)) == q).
  c = np.asarray(codes)
  assert c.min() >= -8 and c.max() <= 7
  repacked, _ = quantize_kv_int4(jnp.asarray(c * np.asarray(scale), jnp.float32))
  assert np.array_equal(np.asarray(repacked), np.asarray(packed))
  # Dequant error bounded by half a quantization step (scale = absmax/7).
  err = np.abs(c * np.asarray(scale) - np.asarray(x))
  assert np.all(err <= np.asarray(scale) / 2 + 1e-6)
  with pytest.raises(ValueError):
    quantize_kv_int4(jnp.zeros((2, 7)))  # odd head dim cannot pack


def _int4_pools(rng, P, Hkv, ps, hd):
  kp, ks = quantize_kv_int4(jnp.asarray(rng.normal(size=(P, Hkv, ps, hd)), jnp.float32))
  vp, vs = quantize_kv_int4(jnp.asarray(rng.normal(size=(P, Hkv, ps, hd)), jnp.float32))
  return kp, ks, vp, vs


def test_paged_kernel_int4_dequant_matches_gather_reference():
  """Packed int4 pools through the kernel (two-dot in-register dequant,
  deinterleaved accumulator) == the gather reference unpacking the SAME
  packed pools — across tile widths including ones that don't divide mp."""
  rng = np.random.default_rng(21)
  B, Hq, Hkv, hd, ps, P = 2, 4, 2, 64, 8, 14
  q = jnp.asarray(rng.normal(size=(B, Hq, hd)), jnp.float32)
  kp, ks, vp, vs = _int4_pools(rng, P, Hkv, ps, hd)
  bt = jnp.asarray([[3, 5, 7, 9, 11, 0], [1, 2, 4, 0, 0, 0]], jnp.int32)
  lengths = jnp.asarray([5 * ps - 3, 2 * ps + 1], jnp.int32)
  ref = paged_gqa_attention_ref(q[:, None], kp, vp, bt, lengths, ps, k_scale_pool_l=ks, v_scale_pool_l=vs)[:, 0]
  for g in (1, 2, 4):
    ker = paged_decode_attention(q, kp, vp, bt, lengths, ps, k_scale_pool_l=ks, v_scale_pool_l=vs, pages_per_step=g, interpret=True)
    assert jnp.allclose(ref, ker, atol=1e-5), f"int4 kernel (tile {g}) diverges"


@pytest.mark.parametrize("pages_per_step", [8, 16])
def test_paged_kernel_wide_tiles_match_reference(pages_per_step):
  """The retuned wide tiles (select_page_tile's B=48/96 verdicts) stay exact
  for int8 pools — including mp that the tile doesn't divide."""
  rng = np.random.default_rng(31)
  B, Hq, Hkv, hd, ps, P = 2, 4, 2, 64, 4, 40
  mp = 18  # not a multiple of 8 or 16
  q = jnp.asarray(rng.normal(size=(B, Hq, hd)), jnp.float32)
  kp = jnp.asarray(rng.integers(-127, 128, size=(P, Hkv, ps, hd)), jnp.int8)
  vp = jnp.asarray(rng.integers(-127, 128, size=(P, Hkv, ps, hd)), jnp.int8)
  ks = jnp.asarray(rng.uniform(0.005, 0.02, size=(P, Hkv, ps, 1)), jnp.float32)
  vs = jnp.asarray(rng.uniform(0.005, 0.02, size=(P, Hkv, ps, 1)), jnp.float32)
  bt = np.zeros((B, mp), np.int32)
  bt[0, :15] = np.arange(1, 16)
  bt[1, :7] = np.arange(20, 27)
  lengths = jnp.asarray([15 * ps - 1, 6 * ps + 2], jnp.int32)
  ref = paged_gqa_attention_ref(q[:, None], kp, vp, jnp.asarray(bt), lengths, ps, k_scale_pool_l=ks, v_scale_pool_l=vs)[:, 0]
  ker = paged_decode_attention(q, kp, vp, jnp.asarray(bt), lengths, ps, k_scale_pool_l=ks, v_scale_pool_l=vs, pages_per_step=pages_per_step, interpret=True)
  assert jnp.allclose(ref, ker, atol=1e-5), f"tile {pages_per_step} diverges"


def test_paged_int4kv_decode_matches_dense_int4kv():
  """Paged int4-KV batched decode == dense int4-KV batched decode token for
  token (both quantize per (token, head) with the same nibble codes — int4
  is exact vs its OWN reference). Covers the packed write path in both
  layouts, the paged prefill's gathered-pool forward, and decode runs
  crossing page boundaries."""
  params, shard = full_model_params(KEY, CFG)
  rng = np.random.default_rng(17)
  B, mp = 4, 128 // PS
  lens = [PS + 2, PS - 1, 7, 2 * PS + 3]
  prompts = [list(rng.integers(1, CFG.vocab_size, size=(s,))) for s in lens]
  S_pad = 48
  tok = np.zeros((B, S_pad), np.int32)
  for i, p in enumerate(prompts):
    tok[i, : len(p)] = p
  prompt_lens = np.asarray(lens, np.int32)

  dense = init_kv_cache(CFG, shard.n_shard_layers, B, 128, quant="int4")
  assert dense["k"].shape[-1] == CFG.cache_k_dim // 2 and dense["k"].dtype == jnp.int8
  last_d, dense = prefill_into_slots(params, CFG, shard, jnp.asarray(tok), dense, jnp.arange(B, dtype=jnp.int32), jnp.asarray(prompt_lens))

  pool = init_paged_pool(CFG, shard.n_shard_layers, 1 + B * mp, PS, quant="int4")
  assert pool["k"].shape[-1] == CFG.cache_k_dim // 2
  bts = np.zeros((B, mp), np.int32)
  for r in range(B):
    bts[r] = range(1 + r * mp, 1 + (r + 1) * mp)
  last_p, pool = prefill_into_pages_many(
    params, CFG, shard, jnp.asarray(tok), pool, jnp.asarray(bts),
    jnp.zeros((B,), jnp.int32), jnp.asarray(prompt_lens), PS,
  )
  assert np.allclose(np.asarray(last_d), np.asarray(last_p), atol=1e-4)
  firsts = np.argmax(np.asarray(last_d), axis=-1).astype(np.int32)
  assert np.array_equal(firsts, np.argmax(np.asarray(last_p), axis=-1))

  tok1 = jnp.asarray(firsts[:, None], jnp.int32)
  positions = jnp.asarray(prompt_lens, jnp.int32)
  active = jnp.ones((B,), bool)
  temps = jnp.zeros((B,), jnp.float32)
  n_steps = PS + 3  # every row's decode crosses at least one page boundary
  td, _, pd, _ = fused_batch_decode(params, CFG, shard, tok1, dense, positions, active, temps, n_steps)
  tp, _, pq, _ = fused_paged_batch_decode(
    params, CFG, shard, tok1, pool, jnp.asarray(bts), positions, active, temps, n_steps, page_size=PS, use_kernel=False
  )
  assert np.array_equal(np.asarray(td), np.asarray(tp))
  assert np.array_equal(np.asarray(pd), np.asarray(pq))


def test_page_tile_dispatch_table(monkeypatch):
  """Shape-aware page-tile verdicts (the r15 retune) + the env force-cap."""
  from xotorch_support_jetson_tpu.inference.paging import select_page_tile
  from xotorch_support_jetson_tpu.ops.paged import _page_tile

  monkeypatch.delenv("XOT_TPU_PAGED_TILE", raising=False)
  # Small batch: bf16 keeps the original G=4; quantized pages (half/quarter
  # the DMA bytes per tile) go one bucket wider.
  assert select_page_tile(16, 1024, "") == 4
  assert select_page_tile(16, 4096, "int8") == 8
  assert select_page_tile(8, 1024, "int4") == 8
  # The dense-knee bucket and beyond: wider tiles cut sequential grid steps.
  assert select_page_tile(48, 1024, "int8") == 8
  assert select_page_tile(48, 32768, "") == 8
  assert select_page_tile(96, 1024, "int8") == 16
  assert select_page_tile(96, 32768, "int4") == 16
  # The kernel clamps the verdict to a power of two <= mp.
  assert _page_tile(6, batch=96, context=6 * 64, kv_quant="int8") == 4
  assert _page_tile(64, batch=96, context=64 * 64, kv_quant="int8") == 16
  assert _page_tile(64, batch=16, context=64 * 64, kv_quant="") == 4
  # XOT_TPU_PAGED_TILE force-caps every shape (the sweep knob).
  monkeypatch.setenv("XOT_TPU_PAGED_TILE", "2")
  assert _page_tile(64, batch=96, context=64 * 64, kv_quant="int8") == 2
  monkeypatch.setenv("XOT_TPU_PAGED_TILE", "32")
  assert _page_tile(64, batch=4, context=64 * 64) == 32


@pytest.mark.parametrize("tile", [1, 4, 8, 16])
@pytest.mark.parametrize("quant", ["", "int8", "int4"])
def test_resolved_path_attribution_matches_dispatch_grid(monkeypatch, tile, quant):
  """Satellite (ISSUE 11): ``resolved_decode_path`` — the metrics
  attribution label — can never silently disagree with the
  ``select_decode_path`` verdict it mirrors, across the full (batch,
  context, quant-mode, tile) grid. The tile axis rides the env force-cap:
  it must never change WHICH path is attributed, only the kernel's
  geometry."""
  from xotorch_support_jetson_tpu.inference.paging import resolved_decode_path, select_decode_path

  monkeypatch.delenv("XOT_TPU_PAGED_KERNEL", raising=False)
  monkeypatch.setenv("XOT_TPU_PAGED_TILE", str(tile))
  for batch in (1, 4, 8, 16, 48, 96):
    for context in (1024, 4096, 32768):
      verdict = select_decode_path(batch, context, quant, platform="tpu")
      resolved = resolved_decode_path(batch, context, quant, paged=True, platform="tpu")
      if verdict == "gather":
        assert resolved == "gather", (batch, context, quant, tile)
      else:  # "kernel" directly; "dense" degrades to kernel inside a paged program
        assert resolved == "kernel", (batch, context, quant, tile)
      # A non-paged layout is always attributed dense; non-TPU pins gather.
      assert resolved_decode_path(batch, context, quant, paged=False, platform="tpu") == "dense"
      assert resolved_decode_path(batch, context, quant, paged=True, platform="cpu") == "gather"


def test_int4_block_math_moves_admission_knee_past_96():
  """The scheduler's default-pool block math at the dense-48 bf16 budget:
  int4 pages cover >= 96 FULL context windows where int8 pages cannot —
  the ISSUE 11 admission-knee criterion, pinned at a production-like
  geometry (hd=64) straight on the shared ``kv_cache_bytes`` formula."""
  from xotorch_support_jetson_tpu.inference.paging import kv_cache_bytes, pages_to_cover

  cfg = tiny_test_config(dim=512, n_heads=8, n_kv_heads=8, max_seq_len=1024)
  assert cfg.head_dim == 64
  ps, n_slots, L = 64, 48, cfg.n_layers
  pages_per_row = pages_to_cover(cfg.max_seq_len, ps)
  # The scheduler's budget baseline: the dense bf16 layout of n_slots rows.
  heads, per_side = cfg.cache_kv_heads, cfg.cache_k_dim + cfg.cache_v_dim
  dense_budget = L * n_slots * pages_per_row * ps * heads * per_side * 2
  pages_int8 = dense_budget // kv_cache_bytes(cfg, L, ps, "int8")
  pages_int4 = dense_budget // kv_cache_bytes(cfg, L, ps, "int4")
  # ~1.88x and ~3.56x the dense page count respectively (hd=64).
  assert pages_int8 < 2 * n_slots * pages_per_row
  assert pages_int4 > 1.8 * pages_int8
  # The knee: 96 full windows fit under int4, not under int8.
  assert pages_int4 >= 96 * pages_per_row
  assert pages_int8 < 96 * pages_per_row


def _engine(params, shard):
  engine = JaxShardedInferenceEngine(use_local_mesh=False)
  engine.load_test_model(shard, CFG, params)
  return engine


def test_scheduler_int4kv_pool_block_math_and_serves(monkeypatch):
  """XOT_TPU_KV_QUANT=int4 end to end through the batched scheduler: the
  default pool is sized by the int4 block math (the shared kv_cache_bytes
  formula against the bf16 dense budget), the pool leaves are packed, the
  quant tag lands on scheduler + tier, and requests serve exactly."""
  from xotorch_support_jetson_tpu.inference.batch_scheduler import BatchedServer
  from xotorch_support_jetson_tpu.inference.paging import kv_cache_bytes

  params, shard = full_model_params(KEY, CFG)
  monkeypatch.setenv("XOT_TPU_PAGED", "1")
  monkeypatch.setenv("XOT_TPU_PAGE_SIZE", str(PS))
  monkeypatch.setenv("XOT_TPU_KV_QUANT", "int4")
  monkeypatch.delenv("XOT_TPU_BATCH_PAGES", raising=False)
  server = BatchedServer(_engine(params, shard), n_slots=2, chunk=2)

  async def run():
    return await server.submit("q4", np.asarray([3, 25, 9], np.int32), max_tokens=4, temp=0.0, top_k=35, eos_ids=(), emit=lambda *_: None)

  out = asyncio.run(run())
  assert len(out) == 4
  mp = 128 // PS
  L = shard.n_shard_layers
  heads, per_side = CFG.cache_kv_heads, CFG.cache_k_dim + CFG.cache_v_dim
  dense_budget = L * server.n_slots * mp * PS * heads * per_side * 2
  expect = dense_budget // kv_cache_bytes(CFG, L, PS, "int4") + 1
  assert server.allocator.n_pages == expect
  int8_pages = dense_budget // kv_cache_bytes(CFG, L, PS, "int8") + 1
  assert server.allocator.n_pages > int8_pages  # strictly beyond int8 block math
  assert server.cache["k"].dtype == jnp.int8
  assert server.cache["k"].shape[-1] == CFG.cache_k_dim // 2  # packed codes
  assert server.kv_quant == "int4"
  if server.tier is not None:
    assert server.tier.kv_quant == "int4"
  server.shutdown()


def test_spec_paged_window_kernel_identity():
  """Satellite (ISSUE 11): the batched-spec VERIFY window routed through the
  tuned kernel (per-position unroll, interpret mode) == the gather
  reference path — for int8 pools, packed int4 pools, and bf16 pools."""
  from xotorch_support_jetson_tpu.models.decoder import paged_window_forward

  params, shard = full_model_params(KEY, CFG)
  rng = np.random.default_rng(41)
  B, W, mp = 2, 3, 128 // PS
  for quant in ("", "int8", "int4"):
    pool = init_paged_pool(CFG, shard.n_shard_layers, 1 + B * mp, PS, quant=quant)
    bts = np.zeros((B, mp), np.int32)
    for r in range(B):
      bts[r] = range(1 + r * mp, 1 + (r + 1) * mp)
    # Seed some prior context through the prefill path so the window reads
    # real pages behind its own writes.
    lens = [PS + 1, 5]
    tok = np.zeros((B, 32), np.int32)
    for i, s in enumerate(lens):
      tok[i, :s] = rng.integers(1, CFG.vocab_size, size=(s,))
    _, pool = prefill_into_pages_many(
      params, CFG, shard, jnp.asarray(tok), pool, jnp.asarray(bts),
      jnp.zeros((B,), jnp.int32), jnp.asarray(lens, np.int32), PS,
    )
    window = jnp.asarray(rng.integers(1, CFG.vocab_size, size=(B, W)), jnp.int32)
    wpos = jnp.asarray([[lens[0] + j for j in range(W)], [lens[1] + j for j in range(W)]], jnp.int32)
    ref_logits, ref_pool = paged_window_forward(params, CFG, shard, window, wpos, dict(pool), jnp.asarray(bts), PS, use_kernel=False)
    ker_logits, ker_pool = paged_window_forward(params, CFG, shard, window, wpos, dict(pool), jnp.asarray(bts), PS, use_kernel=True, interpret=True)
    assert np.allclose(np.asarray(ref_logits), np.asarray(ker_logits), atol=1e-4), f"window kernel diverges ({quant or 'bf16'})"
    assert np.argmax(np.asarray(ref_logits), -1).tolist() == np.argmax(np.asarray(ker_logits), -1).tolist()
    # Pool writes land on the same slots with the same shapes; deeper-layer
    # values may differ in the last ulp (the kernel's online-softmax reduces
    # in a different order than the gather einsum, and layer N's attention
    # feeds layer N+1's K/V), so the write pin is allclose, not byte-equal.
    for name in ref_pool:
      assert ref_pool[name].shape == ker_pool[name].shape
      assert np.allclose(np.asarray(ref_pool[name], np.float32), np.asarray(ker_pool[name], np.float32), atol=1e-2), f"pool writes diverge ({quant}/{name})"


def test_fused_spec_paged_kernel_ab_identity(monkeypatch):
  """Full batched-spec program A/B: use_kernel=True (interpret) emits the
  exact token streams of the gather-reference program — batched speculation
  no longer forfeits the kernel win (ISSUE 11 satellite)."""
  from xotorch_support_jetson_tpu.models.decoder import fused_spec_paged_batch_decode

  params, shard = full_model_params(KEY, CFG)
  params_d, shard_d = full_model_params(jax.random.PRNGKey(5), CFG, "draft")
  rng = np.random.default_rng(53)
  B, mp = 2, 128 // PS
  pool = init_paged_pool(CFG, shard.n_shard_layers, 1 + B * mp, PS, quant="int8")
  cache_d = init_kv_cache(CFG, shard_d.n_shard_layers, B, 128, quant="")
  bts = np.zeros((B, mp), np.int32)
  for r in range(B):
    bts[r] = range(1 + r * mp, 1 + (r + 1) * mp)
  lens = [4, 6]
  tok = np.zeros((B, 16), np.int32)
  for i, s in enumerate(lens):
    tok[i, :s] = rng.integers(1, CFG.vocab_size, size=(s,))
  _, pool = prefill_into_pages_many(
    params, CFG, shard, jnp.asarray(tok), pool, jnp.asarray(bts),
    jnp.zeros((B,), jnp.int32), jnp.asarray(lens, np.int32), PS,
  )
  _, cache_d = prefill_into_slots(params_d, CFG, shard_d, jnp.asarray(tok), cache_d, jnp.arange(B, dtype=jnp.int32), jnp.asarray(lens, np.int32))
  token = jnp.asarray([[9], [11]], jnp.int32)
  positions = jnp.asarray(lens, jnp.int32)
  active = jnp.ones((B,), bool)
  gammas = jnp.asarray([2, 2], jnp.int32)
  temps = jnp.zeros((B,), jnp.float32)
  outs = {}
  for use_kernel in (False, True):
    buf, counts, _n_prop, nxt, npos, _, _ = fused_spec_paged_batch_decode(
      params, CFG, shard, params_d, CFG, shard_d, token, {k: jnp.array(v) for k, v in pool.items()},
      {k: jnp.array(v) for k, v in cache_d.items()}, jnp.asarray(bts), positions, active, gammas, temps,
      n_rounds=2, gamma_max=2, page_size=PS, key=jax.random.PRNGKey(7), use_kernel=use_kernel, interpret=use_kernel,
    )
    counts = np.asarray(counts)
    outs[use_kernel] = [np.asarray(buf)[i, : counts[i]].tolist() for i in range(B)] + [np.asarray(nxt).tolist(), np.asarray(npos).tolist()]
  assert outs[True] == outs[False], f"spec kernel A/B diverged: {outs}"


def test_adopt_guard_active_before_pool_builds(monkeypatch):
  """Review hardening: a disagg decode node can receive SendKvPages BEFORE
  its first request builds the pool. The lazily created tier resolves the
  quant mode eagerly from env/cfg, so a mismatched sender is refused while
  the tier is empty and its byte-geometry guard is still unseeded (the
  exact window the tag guard exists for)."""
  from xotorch_support_jetson_tpu.inference.batch_scheduler import BatchedServer

  params, shard = full_model_params(KEY, CFG)
  monkeypatch.setenv("XOT_TPU_PAGED", "1")
  monkeypatch.setenv("XOT_TPU_PAGE_SIZE", str(PS))
  monkeypatch.setenv("XOT_TPU_KV_QUANT", "int4")
  monkeypatch.delenv("XOT_TPU_KV_TIER", raising=False)
  server = BatchedServer(_engine(params, shard), n_slots=2, chunk=2)
  assert server.cache is None and server.tier is None  # nothing built yet
  hd, H = CFG.cache_k_dim, CFG.cache_kv_heads
  leaves = {
    "k": np.ones((2, 1, H, PS, hd // 2), np.int8),
    "v": np.ones((2, 1, H, PS, hd // 2), np.int8),
    "k_scale": np.ones((2, 1, H, PS, 1), np.float32),
    "v_scale": np.ones((2, 1, H, PS, 1), np.float32),
  }
  # A mismatched (int8) sender is refused up front…
  assert server.adopt_kv_wire([b"early-key"], leaves, quant="int8") == 0
  assert server.kv_quant == "int4" and server.tier is not None and server.tier.kv_quant == "int4"
  assert server.tier.host_pages == 0  # nothing seeded the byte guard
  # …and the matching mode adopts.
  assert server.adopt_kv_wire([b"early-key"], leaves, quant="int4") == 1
  server.shutdown()
